"""CLI: ``python -m tools.analyze [paths...]``.

Exit code 0 when every finding is baselined (or none exist), 1 otherwise --
the contract tests/test_static_analysis.py and ``make lint`` rely on.
"""

from __future__ import annotations

import argparse
import ast
import os
import subprocess
import sys
import time

from tools.analyze import runner

#: Everything the analyzer owns by default: the operator package, its own
#: tooling, and the bench harness (tools/ and bench.py joined the scope once
#: the jit-boundary passes could vet them; pre-existing findings there are
#: grandfathered in tools/analyze/baseline.json).
DEFAULT_PATHS = ["trainingjob_operator_tpu", "tools", "bench.py"]

#: The declared-registry module: a change here re-scopes project passes
#: (see --changed-since) because the registries it declares parameterize
#: findings in *other* files.
CONSTANTS_REL = "trainingjob_operator_tpu/api/constants.py"


def _shard_state_report(paths, root) -> int:
    """``--report shard-state``: build the project context and print the
    TJA027 inventory JSON (docs/STATIC_ANALYSIS.md documents the schema).
    Exit 0 only when every singleton is classified, no registry entry is
    stale, and nothing mutates a constant-classified singleton."""
    import json

    from tools.analyze.checks import shard_state
    from tools.analyze.project import ProjectContext

    contexts = {}
    for abs_path in runner.iter_py_files(paths, root):
        ctx = runner.make_context(abs_path, root)
        contexts[ctx.path] = ctx
    pc = ProjectContext.build(root, contexts)
    doc, ok = shard_state.report(pc)
    print(json.dumps(doc, indent=2, sort_keys=True))
    n = len(doc["singletons"])
    bad = doc["unclassified"]
    print(f"{n} singleton(s), {len(bad)} unclassified, "
          f"{len(doc['stale'])} stale, "
          f"{len(doc['constant_violations'])} constant violation(s)",
          file=sys.stderr)
    return 0 if ok else 1


def _thread_model_report(paths, root) -> int:
    """``--report thread-model``: build the project context and print the
    concurrency model JSON (docs/STATIC_ANALYSIS.md documents the
    schema): thread roles and closures, the MHP matrix, per-singleton
    access evidence (site, via, roles, lock-set), and unwaived counts
    for TJA028-TJA032.  Exit 0 only when all five counts are zero."""
    import json

    from tools.analyze.checks import shard_boundary
    from tools.analyze.project import ProjectContext

    contexts = {}
    for abs_path in runner.iter_py_files(paths, root):
        ctx = runner.make_context(abs_path, root)
        contexts[ctx.path] = ctx
    pc = ProjectContext.build(root, contexts)
    doc, ok = shard_boundary.report(pc)
    print(json.dumps(doc, indent=2, sort_keys=True))
    viol = sum(doc["violations"].values())
    print(f"{len(doc['roles'])} role(s), {len(doc['singletons'])} "
          f"singleton(s), {viol} unwaived concurrency violation(s)",
          file=sys.stderr)
    return 0 if ok else 1


def _spawns_threads(src: str) -> bool:
    """Cheap text gate: does this source (old or new) spawn a thread?"""
    return "Thread(" in src or "ThreadPool" in src


def _git_changed_files(root: str, ref: str) -> set:
    """Repo-relative .py files that differ from ``ref`` (committed diff,
    staged, unstaged, and untracked)."""
    changed = set()
    diff = subprocess.run(
        ["git", "diff", "--name-only", "--diff-filter=d", ref, "--"],
        cwd=root, capture_output=True, text=True, check=True)
    changed.update(line.strip() for line in diff.stdout.splitlines())
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=root, capture_output=True, text=True, check=True)
    changed.update(line.strip() for line in untracked.stdout.splitlines())
    return {c for c in changed if c.endswith(".py")}


def _ast_changed(root: str, ref: str, rel: str) -> bool:
    """True when ``rel``'s AST differs from its content at ``ref`` --
    comment/formatting-only edits don't re-lint the file."""
    show = subprocess.run(["git", "show", f"{ref}:{rel}"], cwd=root,
                          capture_output=True, text=True)
    if show.returncode != 0:
        return True   # new file (or unreadable at ref): lint it
    try:
        old = ast.dump(ast.parse(show.stdout))
        with open(os.path.join(root, rel), "r", encoding="utf-8",
                  errors="replace") as fh:
            new = ast.dump(ast.parse(fh.read()))
    except SyntaxError:
        return True   # let py-compat report it
    return old != new


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="AST-based operator lint (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=DEFAULT_PATHS,
                    help="files or directories to analyze "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--format", choices=("text", "json", "github", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of grandfathered findings "
                         f"(default: {runner.DEFAULT_BASELINE} if it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring any baseline")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="snapshot current findings as the baseline and exit 0")
    ap.add_argument("--checks", default=None,
                    help="comma-separated subset of check names or IDs")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument("--changed-since", metavar="REF", default=None,
                    help="incremental mode: lint only files whose AST "
                         "differs from REF (file passes skip unchanged "
                         "files; project passes still build the full "
                         "context but report only into changed files; "
                         "a change to api/constants.py widens project "
                         "passes back to the full tree, since registry "
                         "edits land findings in unchanged files)")
    ap.add_argument("--report", choices=("shard-state", "thread-model"),
                    default=None,
                    help="emit a machine-readable inventory instead of "
                         "findings: 'shard-state' prints the TJA027 "
                         "module-level mutable-singleton inventory as "
                         "JSON and exits nonzero when it is not clean "
                         "(unclassified/stale/constant-mutated); "
                         "'thread-model' prints the whole-program "
                         "concurrency model (roles, closures, MHP "
                         "matrix, per-singleton access evidence) and "
                         "exits nonzero when any of TJA028-TJA032 has "
                         "unwaived findings")
    ap.add_argument("--max-seconds", type=float, default=None, metavar="S",
                    help="fail (exit 1) when the analysis itself takes longer "
                         "than S wall-clock seconds -- a CI budget proving "
                         "the whole-program layer stays cheap")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not update the incremental result "
                         "cache (tools/analyze/cache.py); full runs over an "
                         "unchanged tree otherwise replay their findings "
                         "from .analyze-cache.json")
    args = ap.parse_args(argv)

    # Run-once batch process over millions of short-lived AST nodes: the
    # collector's gen-2 sweeps cost a few hundred ms of the --max-seconds
    # budget and reclaim nothing the process exit won't.  Reference cycles
    # (AST parent links, ProjectContext cross-references) just stay alive
    # until exit.
    import gc
    gc.disable()

    if args.list_checks:
        for cid, name in sorted(runner.all_checks().items()):
            kind = "project" if name in runner.PROJECT_REGISTRY else "file"
            print(f"{cid}  {name}  [{kind}]")
        return 0

    only = args.checks.split(",") if args.checks else None
    paths = args.paths or DEFAULT_PATHS
    root = os.getcwd()

    # Load the check registry before the --max-seconds clock starts: the
    # budget gates the *analysis*, and the 32 check-module imports are fixed
    # interpreter startup, not per-tree work.
    runner._load_checks()

    if args.report == "shard-state":
        return _shard_state_report(paths, root)
    if args.report == "thread-model":
        return _thread_model_report(paths, root)

    started = time.monotonic()
    report_only = None
    if args.changed_since:
        try:
            candidates = _git_changed_files(root, args.changed_since)
        except (subprocess.CalledProcessError, FileNotFoundError) as exc:
            print(f"--changed-since: cannot diff against "
                  f"{args.changed_since!r}: {exc}", file=sys.stderr)
            return 2
        report_only = {rel for rel in candidates
                       if os.path.exists(os.path.join(root, rel))
                       and _ast_changed(root, args.changed_since, rel)}
        if not report_only:
            print(f"0 finding(s) in "
                  f"{time.monotonic() - started:.2f}s (no AST-changed "
                  f"files since {args.changed_since})", file=sys.stderr)
            return 0
        if CONSTANTS_REL in report_only:
            # The registries in api/constants.py (EVENT_REASONS,
            # PHASE_TRANSITIONS, SHARD_STATE_REGISTRY, ...) parameterize
            # the project passes: editing one lands findings in files
            # that did not change.  Fall back to a full run.
            print(f"{CONSTANTS_REL} changed: registry edits invalidate "
                  "incremental scoping, re-running project passes "
                  "tree-wide", file=sys.stderr)
            report_only = None
        if report_only is not None:
            # A Thread-spawn edit (added, removed, or moved) changes the
            # thread model's roles and MHP relation, which parameterize
            # TJA028-TJA032 findings in *unchanged* files -- same story
            # as a registry edit.  Check both sides of the diff so
            # deleting a spawn also widens.
            for rel in sorted(report_only):
                try:
                    with open(os.path.join(root, rel), "r",
                              encoding="utf-8", errors="replace") as fh:
                        new_src = fh.read()
                except OSError:
                    new_src = ""
                show = subprocess.run(
                    ["git", "show", f"{args.changed_since}:{rel}"],
                    cwd=root, capture_output=True, text=True)
                old_src = show.stdout if show.returncode == 0 else ""
                if _spawns_threads(new_src) or _spawns_threads(old_src):
                    print(f"{rel} changed and spawns threads: thread-"
                          "model edits invalidate incremental scoping, "
                          "re-running project passes tree-wide",
                          file=sys.stderr)
                    report_only = None
                    break

    # Plain full runs (the ``make lint`` shape) replay cached findings when
    # no analyzed file -- nor the analyzer itself -- changed since the last
    # run.  Scoped or snapshot runs always analyze (cache.py).
    cacheable = not (args.no_cache or only or report_only is not None
                     or args.changed_since or args.write_baseline)
    cached = False
    fp = ""
    if cacheable:
        from tools.analyze import cache
        fp = cache.fingerprint(runner.iter_py_files(paths, root), root)
        hit = cache.load(root, paths, fp)
        if hit is not None:
            findings, cached = hit, True
    if not cached:
        findings = runner.run_checks(paths, root=root, only=only,
                                     report_only=report_only)
        if cacheable:
            from tools.analyze import cache
            cache.store(root, paths, fp, findings)
    elapsed = time.monotonic() - started

    if args.write_baseline:
        n = runner.write_baseline(args.write_baseline, findings)
        print(f"wrote {n} baselined finding(s) to {args.write_baseline}",
              file=sys.stderr)
        return 0

    suppressed = 0
    if not args.no_baseline:
        baseline_path = args.baseline or (
            runner.DEFAULT_BASELINE
            if os.path.exists(runner.DEFAULT_BASELINE) else None)
        if baseline_path:
            findings, suppressed = runner.apply_baseline(
                findings, runner.load_baseline(baseline_path))

    out = runner.format_findings(findings, args.format)
    if out.strip():
        print(out, end="")
    summary = f"{len(findings)} finding(s)"
    if suppressed:
        summary += f", {suppressed} baselined"
    summary += f" in {elapsed:.2f}s"
    if cached:
        summary += " (cached)"
    print(summary, file=sys.stderr)
    if args.max_seconds is not None and elapsed > args.max_seconds:
        print(f"analysis took {elapsed:.2f}s, over the --max-seconds "
              f"{args.max_seconds:g} budget", file=sys.stderr)
        return 1
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
