"""CLI: ``python -m tools.analyze [paths...]``.

Exit code 0 when every finding is baselined (or none exist), 1 otherwise --
the contract tests/test_static_analysis.py and ``make lint`` rely on.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from tools.analyze import runner


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="AST-based operator lint (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=["trainingjob_operator_tpu"],
                    help="files or directories to analyze "
                         "(default: trainingjob_operator_tpu)")
    ap.add_argument("--format", choices=("text", "json", "github", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of grandfathered findings "
                         f"(default: {runner.DEFAULT_BASELINE} if it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring any baseline")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="snapshot current findings as the baseline and exit 0")
    ap.add_argument("--checks", default=None,
                    help="comma-separated subset of check names or IDs")
    ap.add_argument("--list-checks", action="store_true")
    ap.add_argument("--max-seconds", type=float, default=None, metavar="S",
                    help="fail (exit 1) when the analysis itself takes longer "
                         "than S wall-clock seconds -- a CI budget proving "
                         "the whole-program layer stays cheap")
    args = ap.parse_args(argv)

    if args.list_checks:
        for cid, name in sorted(runner.all_checks().items()):
            kind = "project" if name in runner.PROJECT_REGISTRY else "file"
            print(f"{cid}  {name}  [{kind}]")
        return 0

    only = args.checks.split(",") if args.checks else None
    paths = args.paths or ["trainingjob_operator_tpu"]
    started = time.monotonic()
    findings = runner.run_checks(paths, root=os.getcwd(), only=only)
    elapsed = time.monotonic() - started

    if args.write_baseline:
        n = runner.write_baseline(args.write_baseline, findings)
        print(f"wrote {n} baselined finding(s) to {args.write_baseline}",
              file=sys.stderr)
        return 0

    suppressed = 0
    if not args.no_baseline:
        baseline_path = args.baseline or (
            runner.DEFAULT_BASELINE
            if os.path.exists(runner.DEFAULT_BASELINE) else None)
        if baseline_path:
            findings, suppressed = runner.apply_baseline(
                findings, runner.load_baseline(baseline_path))

    out = runner.format_findings(findings, args.format)
    if out.strip():
        print(out, end="")
    summary = f"{len(findings)} finding(s)"
    if suppressed:
        summary += f", {suppressed} baselined"
    summary += f" in {elapsed:.2f}s"
    print(summary, file=sys.stderr)
    if args.max_seconds is not None and elapsed > args.max_seconds:
        print(f"analysis took {elapsed:.2f}s, over the --max-seconds "
              f"{args.max_seconds:g} budget", file=sys.stderr)
        return 1
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
