"""Determinism layer shared by TJA024-TJA026 (docs/STATIC_ANALYSIS.md).

The robustness gates (chaos-smoke, node-chaos-smoke, recovery-smoke) all
rest on one contract: same seed => byte-identical ``ChaosPlan.digest()``,
phase counts, and incident-bundle reassembly.  The smokes prove it
dynamically for the seeds they happen to run; this layer proves the
*absence of the bug classes* that break it for some other seed:

- **sources** of nondeterminism: wall clock (``time.time`` and friends),
  OS entropy (``os.urandom``, ``uuid.uuid4``, ``secrets``), the global
  ``random`` module state, ``id()``/default ``repr`` (address-dependent),
  and unsorted ``set`` materialization (hash-randomization-dependent);
- **sinks** that pin bytes: ``canonical()``/``digest()`` methods,
  ``hashlib`` constructors/updates, sorted-keys ``json.dumps``;
- **scope** where *any* unseeded randomness is illegal, not just flows
  that reach a digest: the plan generators and the event kernel
  (``DETERMINISM_SCOPE``).

Everything here is built **once per ProjectContext** and memoized on it,
exactly like ``jit_boundary.boundary()``: four passes share one sweep over
the per-file ASTs the runner already parsed.  ``BUILD_COUNT`` exists so
tests can assert the single build (the 2 s ``make lint`` budget depends on
it).

Like the rest of the analyzer this is a conservative syntactic
approximation: taint is tracked through local assignment chains and
project-function returns, not through object attributes or containers.
The passes only report what they can witness; waivers cover deliberate
nondeterminism (docs/STATIC_ANALYSIS.md lists the inventory).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import walk_fast
from tools.analyze.jit_boundary import is_test_path
from tools.analyze.project import ModuleInfo, ProjectContext, _dotted

PKG = "trainingjob_operator_tpu"

#: Paths (dir prefixes ending in "/" or exact files) where *every*
#: randomness source must be an explicitly seeded ``random.Random``:
#: the chaos/churn plan generators, the chaos injection proxies, and the
#: event-driven sim kernel whose (deadline, seq) ordering the phase-count
#: determinism rests on.
DETERMINISM_SCOPE = (
    f"{PKG}/fleet/",
    f"{PKG}/client/chaos.py",
    f"{PKG}/runtime/sim.py",
    f"{PKG}/runtime/events.py",
)

#: Built exactly once per ProjectContext (tests assert this, like
#: jit_boundary.BUILD_COUNT).
BUILD_COUNT = 0

# -- source / sink tables -----------------------------------------------------

#: Wall-clock reads: value differs run to run, so any digest it reaches
#: differs run to run.
WALL_CLOCK = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})

#: OS entropy: fresh randomness on every call, unseedable by design.
OS_ENTROPY = frozenset({
    "os.urandom", "uuid.uuid1", "uuid.uuid4",
    "secrets.token_bytes", "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.randbits", "secrets.choice", "secrets.randbelow",
})

#: Module-level ``random.*`` draw/state functions -- the shared global
#: generator whose state any import may perturb (the classic "works until
#: another module draws first" seed-stability bug).
GLOBAL_RANDOM = frozenset({
    "random.random", "random.randint", "random.randrange", "random.uniform",
    "random.choice", "random.choices", "random.shuffle", "random.sample",
    "random.gauss", "random.normalvariate", "random.expovariate",
    "random.betavariate", "random.triangular", "random.vonmisesvariate",
    "random.paretovariate", "random.weibullvariate", "random.lognormvariate",
    "random.getrandbits", "random.randbytes", "random.seed",
    "random.setstate", "random.getstate",
})

#: Process-address sources: ``id()`` (and default ``repr``, which embeds
#: it) differ per process, so they are digest poison but harmless for
#: control flow.
ADDRESS_SOURCES = frozenset({"id", "repr", "ascii"})

#: hashlib constructor leaves (``hashlib.sha256(...)`` et al).
HASHLIB_CTORS = frozenset({
    "hashlib.md5", "hashlib.sha1", "hashlib.sha224", "hashlib.sha256",
    "hashlib.sha384", "hashlib.sha512", "hashlib.blake2b",
    "hashlib.blake2s", "hashlib.sha3_256", "hashlib.sha3_512",
    "hashlib.new",
})

#: Method names that pin bytes when *called with arguments* -- the
#: repo-wide canonical/digest idiom (fleet/chaos.py, obs/incident.py).
DIGEST_METHODS = frozenset({"canonical", "digest", "hexdigest"})

#: Set-producing method names (receiver set-typed => result set-typed).
_SET_METHODS = frozenset({
    "union", "intersection", "difference", "symmetric_difference", "copy",
})


def in_scope(rel_path: str) -> bool:
    """Whether ``rel_path`` is inside the strict determinism scope."""
    for p in DETERMINISM_SCOPE:
        if (rel_path.startswith(p) if p.endswith("/") else rel_path == p):
            return True
    return False


def canonical_callee(mod: Optional[ModuleInfo],
                     func: ast.expr) -> Optional[str]:
    """Canonical dotted name of a call target, with the head segment
    resolved through the module's import aliases: ``monotonic()`` after
    ``from time import monotonic`` -> ``time.monotonic``; ``np.random.rand``
    after ``import numpy as np`` -> ``numpy.random.rand``.  Attribute
    chains rooted at non-imported names (``rng.random``) come back verbatim
    and match no source table."""
    dotted = _dotted(func)
    if dotted is None:
        return None
    head, sep, rest = dotted.partition(".")
    if mod is not None:
        target = mod.imports.get(head)
        if target is not None:
            return f"{target}.{rest}" if sep else target
    return dotted


# -- per-function records -----------------------------------------------------

@dataclass
class FnRec:
    """One function or method body, pre-digested for the taint passes."""
    qual: str                 # "pkg.mod.fn" | "pkg.mod.Class.method"
    node: ast.AST = None
    path: str = ""
    module: str = ""
    #: simple-Name assignments in document order: (names, value expr).
    assigns: List[Tuple[Tuple[str, ...], ast.expr]] = field(
        default_factory=list)
    #: return value expressions.
    returns: List[ast.expr] = field(default_factory=list)
    #: local names bound to set-typed values (fixpoint over assigns).
    set_names: Set[str] = field(default_factory=set)
    #: local names bound to hashlib hasher objects (``h = sha256()``).
    hasher_names: Set[str] = field(default_factory=set)


@dataclass
class DetFacts:
    """The memoized determinism layer: every FnRec in the analyzed package
    (tests excluded), plus the returns-nondeterministic fixpoint."""
    #: qual -> record, package functions and methods only.
    fns: Dict[str, FnRec] = field(default_factory=dict)
    #: per-file: rel path -> records in that file (document order).
    by_path: Dict[str, List[FnRec]] = field(default_factory=dict)
    #: quals whose return value carries a nondeterminism source.
    tainted_returns: Set[str] = field(default_factory=set)
    #: module-level names bound to sets, per module dotted name.
    module_set_names: Dict[str, Set[str]] = field(default_factory=dict)


def facts(pc: ProjectContext) -> DetFacts:
    """The determinism facts for this run, built once and memoized on
    ``pc`` (the TJA024/025/026 passes all start here)."""
    cached = getattr(pc, "_determinism_facts", None)
    if cached is not None:
        return cached
    global BUILD_COUNT
    BUILD_COUNT += 1
    df = _build(pc)
    pc._determinism_facts = df
    return df


def _build(pc: ProjectContext) -> DetFacts:
    df = DetFacts()
    for rel, ctx in pc.files.items():
        if ctx.tree is None or is_test_path(rel):
            continue
        mod = pc.module_of_path(rel)
        if mod is None:
            continue
        msets: Set[str] = set()
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _is_set_expr(mod, None, node.value)):
                # Includes frozenset(...) constants: immutable, but their
                # iteration order is still hash-randomization-dependent.
                msets.add(node.targets[0].id)
        df.module_set_names[mod.name] = msets
        recs = _collect_file(rel, mod, ctx)
        df.by_path[rel] = recs
        for rec in recs:
            df.fns[rec.qual] = rec
    _returns_fixpoint(pc, df)
    return df


def _collect_file(rel: str, mod: ModuleInfo, ctx) -> List[FnRec]:
    """One sweep over the file's cached Assign/Return buckets, attributed
    to the enclosing function via the shared parents map (the same trick
    project.py uses for self-attribute inference)."""
    recs: List[FnRec] = []
    by_fn: Dict[int, FnRec] = {}
    parents = ctx.parents

    def rec_for(node: ast.AST) -> Optional[FnRec]:
        anc = parents.get(id(node))
        while anc is not None:
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                got = by_fn.get(id(anc))
                if got is None:
                    qual = _qual_of(mod, ctx, anc)
                    got = FnRec(qual=qual, node=anc, path=rel,
                                module=mod.name)
                    by_fn[id(anc)] = got
                    recs.append(got)
                return got
            anc = parents.get(id(anc))
        return None

    for sub in ctx.by_type(ast.Assign):
        names = tuple(t.id for t in sub.targets if isinstance(t, ast.Name))
        if not names:
            continue
        rec = rec_for(sub)
        if rec is not None:
            rec.assigns.append((names, sub.value))
    for sub in ctx.by_type(ast.AnnAssign):
        if sub.value is None or not isinstance(sub.target, ast.Name):
            continue
        rec = rec_for(sub)
        if rec is not None:
            rec.assigns.append(((sub.target.id,), sub.value))
    for sub in ctx.by_type(ast.Return):
        if sub.value is None:
            continue
        rec = rec_for(sub)
        if rec is not None:
            rec.returns.append(sub.value)
    for rec in recs:
        _infer_locals(mod, rec)
    return recs


def _qual_of(mod: ModuleInfo, ctx, fn_node: ast.AST) -> str:
    parents = ctx.parents
    parts = [fn_node.name]
    anc = parents.get(id(fn_node))
    while anc is not None:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(anc.name)
        anc = parents.get(id(anc))
    return ".".join([mod.name] + list(reversed(parts)))


def _infer_locals(mod: ModuleInfo, rec: FnRec) -> None:
    """Two-round fixpoint over the assign list: which locals are
    set-typed, which hold hashlib hasher objects."""
    for _ in range(2):
        changed = False
        for names, value in rec.assigns:
            if _is_set_expr(mod, rec, value):
                for n in names:
                    if n not in rec.set_names:
                        rec.set_names.add(n)
                        changed = True
            if isinstance(value, ast.Call):
                canon = canonical_callee(mod, value.func)
                if canon in HASHLIB_CTORS:
                    for n in names:
                        if n not in rec.hasher_names:
                            rec.hasher_names.add(n)
                            changed = True
        if not changed:
            break


def _is_set_expr(mod: ModuleInfo, rec: Optional[FnRec], expr: ast.expr,
                 df: Optional["DetFacts"] = None) -> bool:
    """Whether ``expr`` is (syntactically) set-typed: displays,
    comprehensions, set()/frozenset() calls, set-algebra BinOps,
    set-producing methods on set-typed receivers, and names inferred
    set-typed locally or at module level (``df`` adds the cross-checked
    module-level set constants, frozensets included)."""
    cls = expr.__class__
    if cls is ast.Set or cls is ast.SetComp:
        return True
    if cls is ast.Name:
        if rec is not None and expr.id in rec.set_names:
            return True
        if (df is not None and mod is not None
                and expr.id in df.module_set_names.get(mod.name, ())):
            return True
        got = mod.global_mutables.get(expr.id) if mod is not None else None
        return got is not None and got[0] == "set"
    if cls is ast.Call:
        fn = expr.func
        leaf = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if leaf in ("set", "frozenset"):
            return True
        if (leaf in _SET_METHODS and isinstance(fn, ast.Attribute)
                and _is_set_expr(mod, rec, fn.value, df)):
            return True
        return False
    if cls is ast.BinOp and isinstance(
            expr.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return (_is_set_expr(mod, rec, expr.left, df)
                or _is_set_expr(mod, rec, expr.right, df))
    return False


def is_set_expr(mod: ModuleInfo, rec: Optional[FnRec], expr: ast.expr,
                df: Optional["DetFacts"] = None) -> bool:
    """Public alias for the checks (see ``_is_set_expr``)."""
    return _is_set_expr(mod, rec, expr, df)


# -- source classification ----------------------------------------------------

def source_kind(mod: Optional[ModuleInfo],
                call: ast.Call) -> Optional[str]:
    """Human-readable nondeterminism-source label for a call expression,
    or None.  This is the TJA025 source table; TJA024 adds the
    scope-specific constructs on top (see unseeded_randomness.py)."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in ADDRESS_SOURCES:
        if mod is not None and (fn.id in mod.imports
                                or fn.id in mod.functions):
            return None     # shadowed builtin
        return f"{fn.id}() (process-address-dependent)"
    canon = canonical_callee(mod, fn)
    if canon is None:
        return None
    if canon in WALL_CLOCK:
        return f"wall clock ({canon})"
    if canon in OS_ENTROPY:
        return f"OS entropy ({canon})"
    if canon in GLOBAL_RANDOM:
        return f"global random state ({canon})"
    if canon == "random.Random" and not call.args:
        return "unseeded random.Random()"
    if canon == "random.SystemRandom":
        return "OS entropy (random.SystemRandom)"
    if canon.startswith("numpy.random.") and not (
            canon == "numpy.random.default_rng" and call.args):
        return f"legacy numpy global RNG ({canon})"
    return None


# -- returns-nondeterministic fixpoint ----------------------------------------

def _callee_quals(mod: ModuleInfo, rec: Optional[FnRec],
                  call: ast.Call) -> List[str]:
    """Project-function quals a call may target: plain names resolved
    through the module table and imports, ``self.m()`` resolved against
    the enclosing class's methods."""
    fn = call.func
    out: List[str] = []
    if isinstance(fn, ast.Name):
        if fn.id in mod.functions:
            out.append(f"{mod.name}.{fn.id}")
        target = mod.imports.get(fn.id)
        if target is not None:
            out.append(target)
    elif isinstance(fn, ast.Attribute):
        recv = fn.value
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                if rec is not None:
                    # qual prefix: strip the method leaf off rec.qual.
                    cls_qual = rec.qual.rpartition(".")[0]
                    out.append(f"{cls_qual}.{fn.attr}")
            else:
                target = mod.imports.get(recv.id)
                if target is not None:
                    out.append(f"{target}.{fn.attr}")
    return out


def _expr_source(mod: ModuleInfo, rec: Optional[FnRec], expr: ast.expr,
                 vtainted: Set[str], df: DetFacts
                 ) -> Optional[Tuple[str, int]]:
    """First value-taint witness inside ``expr``: a source call, a
    value-tainted local, or a call to a returns-nondeterministic project
    function.  Returns (label, lineno) or None."""
    for node in walk_fast(expr):
        cls = node.__class__
        if cls is ast.Name:
            if node.id in vtainted:
                return (f"nondeterministic local {node.id!r}", node.lineno)
        elif cls is ast.Call:
            kind = source_kind(mod, node)
            if kind is not None:
                return (kind, node.lineno)
            for q in _callee_quals(mod, rec, node):
                if q in df.tainted_returns:
                    leaf = q.rpartition(".")[2]
                    return (f"call to {leaf}() "
                            "(returns a nondeterministic value)",
                            node.lineno)
    return None


def local_value_taint(mod: ModuleInfo, rec: FnRec,
                      df: DetFacts) -> Set[str]:
    """Locals carrying a nondeterministic *value* (wall clock, entropy,
    address), via a small assignment-chain fixpoint in document order."""
    tainted: Set[str] = set()
    for _ in range(3):
        changed = False
        for names, value in rec.assigns:
            if all(n in tainted for n in names):
                continue
            if _expr_source(mod, rec, value, tainted, df) is not None:
                for n in names:
                    if n not in tainted:
                        tainted.add(n)
                        changed = True
        if not changed:
            break
    return tainted


def _returns_fixpoint(pc: ProjectContext, df: DetFacts) -> None:
    """Interprocedural closure: a function is returns-nondeterministic
    when any return expression carries a source, a source-tainted local,
    or a call to an already-tainted function.

    Delta-driven for the 2s lint budget: taint can only *originate* at a
    direct source call, so round one fully evaluates just the functions
    containing one (a cheap call-leaf scan finds them, and collects each
    function's referenced project quals along the way); afterwards a
    pending function is re-examined only when a qual it references newly
    became tainted, instead of re-running the whole-package taint walk
    every round."""
    mods = pc.modules

    def evaluate(mod: ModuleInfo, rec: FnRec) -> bool:
        vt = local_value_taint(mod, rec, df)
        for r in rec.returns:
            if _expr_source(mod, rec, r, vt, df) is not None:
                return True
        return False

    pending: Dict[str, Tuple[FnRec, Set[str]]] = {}
    newly: Set[str] = set()
    for rec in df.fns.values():
        if not rec.returns:
            continue
        mod = mods.get(rec.module)
        if mod is None:
            continue
        direct = False
        refs: Set[str] = set()
        for expr in [v for _n, v in rec.assigns] + rec.returns:
            for node in walk_fast(expr):
                if node.__class__ is not ast.Call:
                    continue
                if not direct and source_kind(mod, node) is not None:
                    direct = True
                refs.update(_callee_quals(mod, rec, node))
        if direct and evaluate(mod, rec):
            df.tainted_returns.add(rec.qual)
            newly.add(rec.qual)
        else:
            pending[rec.qual] = (rec, refs)
    while newly:
        delta, newly = newly, set()
        for qual in list(pending):
            rec, refs = pending[qual]
            if refs.isdisjoint(delta):
                continue
            mod = mods.get(rec.module)
            if evaluate(mod, rec):
                df.tainted_returns.add(qual)
                newly.add(qual)
                del pending[qual]
