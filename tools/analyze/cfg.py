"""Per-function control-flow graphs over the shared per-file ASTs.

The per-file passes (TJA001-TJA009) and the whole-program layer (TJA010+)
reason about *names*: what is called, what is acquired, what is emitted.  The
operator's hardest reliability properties are about *paths*: a socket must be
closed on every exception path, a flag flipped before a blocking call must be
restored in a ``finally``, a retry loop must back off on its back edge.  This
module gives each function body a small CFG so the path-sensitive passes
(TJA015-TJA019) can witness those paths instead of guessing from lexical
shape.

Design (the CPython ``symtable``+compile split, staticcheck's function-body
facts):

- **Basic blocks** hold maximal straight-line statement runs.  Branch points
  (``if``/``while``/``for``) keep the *branching statement itself* as the
  block's last entry -- only its test/iter expression is evaluated there
  (``stmt_expressions`` says exactly what a statement evaluates at its block
  position).
- **Edges** are labeled: ``fall``, ``true``/``false``, ``loop`` (back edge),
  ``break``/``continue``, ``return``, ``except`` (dispatch -> handler),
  ``finally`` and ``exc``/``raise`` (exceptional flow).
- **Exceptions** are modeled at statement granularity: a statement *may
  raise* when it is a ``raise``/``assert`` or evaluates a call.  Every block
  with a raising statement gets one ``exc`` edge to the innermost active
  handler -- a synthetic *dispatch* block fanning out to the ``except``
  clauses -- or, uncaught, to the function's ``exc_exit``.
- **finally** bodies are *duplicated per exit kind* (normal / exceptional /
  return / break / continue), the textbook linearization: the exceptional
  copy ends at the outer handler, so "the restore happens in a finally" is
  visible as an ordinary kill on the exception path, no special-casing in
  the dataflow clients.

CFGs are built lazily and memoized on ``FileContext`` (``ctx.cfg(fn)``), so
five passes asking for the same function share one build and the analyzer
stays inside its 2 s ``--max-seconds`` budget.  ``BUILD_COUNT`` exists for
the tests to prove exactly that.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from tools.analyze.findings import walk_fast

#: Incremented by every real CFG construction; tests assert builds == number
#: of distinct functions, i.e. the FileContext memo actually shares.
BUILD_COUNT = 0

#: Edge kinds considered *exceptional*: dataflow propagates ``exc_out`` (not
#: ``out``) along these.
EXC_KINDS = frozenset(("exc", "raise"))

#: Edge kinds a normal-control-flow walk follows.
NORMAL_KINDS = frozenset(("fall", "true", "false", "loop", "break",
                          "continue", "return", "except", "finally", "case"))


class Block:
    """One basic block.  ``stmts`` are real AST nodes (statements, or an
    ``ast.ExceptHandler`` marking the match+bind point at a handler entry);
    ``raising`` is a parallel bool list (statement may raise here)."""

    __slots__ = ("bid", "label", "stmts", "raising", "succs", "preds",
                 "handlers")

    def __init__(self, bid: int, label: str = ""):
        self.bid = bid
        self.label = label
        self.stmts: List[ast.AST] = []
        self.raising: List[bool] = []
        self.succs: List[Tuple["Block", str]] = []
        self.preds: List[Tuple["Block", str]] = []
        #: dispatch blocks only: the (handler node, entry block) fan-out.
        self.handlers: List[Tuple[ast.ExceptHandler, "Block"]] = []

    def edge(self, other: "Block", kind: str) -> None:
        if (other, kind) not in self.succs:
            self.succs.append((other, kind))
            other.preds.append((self, kind))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<B{self.bid}{':' + self.label if self.label else ''}>"


class CFG:
    """Control-flow graph of one function body."""

    def __init__(self, func: ast.AST):
        self.func = func
        self.blocks: List[Block] = []
        self.entry: Optional[Block] = None
        #: normal exit: explicit returns and falling off the end.
        self.exit: Optional[Block] = None
        #: exceptional exit: exceptions no handler in the function catches.
        self.exc_exit: Optional[Block] = None
        #: id(stmt) -> first block holding it (unique except finally copies).
        self.block_of: Dict[int, Block] = {}

    def walk_blocks(self, start: Block, kinds: frozenset = NORMAL_KINDS
                    ) -> Iterable[Block]:
        """Blocks reachable from ``start`` along edges in ``kinds``."""
        seen = {start.bid}
        stack = [start]
        while stack:
            b = stack.pop()
            yield b
            for nxt, kind in b.succs:
                if kind in kinds and nxt.bid not in seen:
                    seen.add(nxt.bid)
                    stack.append(nxt)

    def reaches(self, start: Block, goal: Block,
                blocked: Optional[set] = None,
                kinds: frozenset = NORMAL_KINDS) -> bool:
        """True when ``goal`` is reachable from ``start`` without entering a
        block whose bid is in ``blocked`` (path-sensitive "is there a way
        around the guard" queries)."""
        blocked = blocked or set()
        if start.bid in blocked:
            return False
        seen = {start.bid}
        stack = [start]
        while stack:
            b = stack.pop()
            if b.bid == goal.bid:
                return True
            for nxt, kind in b.succs:
                if (kind in kinds and nxt.bid not in seen
                        and nxt.bid not in blocked):
                    seen.add(nxt.bid)
                    stack.append(nxt)
        return False


def stmt_expressions(stmt: ast.AST) -> List[ast.expr]:
    """The expressions a statement evaluates *at its block position*.  A
    branching statement appears in a block only for its test/iter; its body
    lives in successor blocks."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [e for e in (stmt.test, stmt.msg) if e is not None]
    if isinstance(stmt, ast.Assign):
        return [stmt.value] + list(stmt.targets)
    if isinstance(stmt, ast.AnnAssign):
        return [e for e in (stmt.value, stmt.target) if e is not None]
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value, stmt.target]
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Delete):
        return list(stmt.targets)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return list(stmt.decorator_list)   # the def executes; the body later
    if isinstance(stmt, ast.ClassDef):
        return list(stmt.decorator_list) + list(stmt.bases)
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, ast.ExceptHandler):
        return []
    return []


def may_raise(stmt: ast.AST) -> bool:
    """Conservative witness that executing ``stmt`` at its block position can
    raise: explicit raise/assert, or any call in its evaluated expressions.
    Attribute/subscript faults are deliberately NOT counted -- every line
    would then be an exception source and path findings would drown."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    for expr in stmt_expressions(stmt):
        for node in walk_fast(expr):
            if isinstance(node, (ast.Call, ast.Await, ast.Yield,
                                 ast.YieldFrom)):
                return True
    return False


def handler_type_names(handler: ast.ExceptHandler) -> List[str]:
    """Leaf exception-type names an ``except`` clause catches; ``["*"]``
    for a bare ``except:``."""
    t = handler.type
    if t is None:
        return ["*"]
    items = t.elts if isinstance(t, ast.Tuple) else [t]
    names: List[str] = []
    for item in items:
        node = item
        while isinstance(node, ast.Attribute):
            node = node.value  # socket.timeout -> keep the leaf attr below
        if isinstance(item, ast.Attribute):
            names.append(item.attr)
        elif isinstance(item, ast.Name):
            names.append(item.id)
        else:
            names.append("*")  # dynamic: assume it catches anything
    return names


def _catches_all(handlers: List[ast.ExceptHandler]) -> bool:
    for h in handlers:
        names = handler_type_names(h)
        if "*" in names or "BaseException" in names or "Exception" in names:
            return True
    return False


class _Frame:
    """One active exception-routing frame: a handler dispatch block, or a
    pending ``finally`` whose exceptional copy is built lazily."""

    __slots__ = ("kind", "dispatch", "node", "exc_copy")

    def __init__(self, kind: str, dispatch: Optional[Block] = None,
                 node: Optional[ast.Try] = None):
        self.kind = kind            # "dispatch" | "finally"
        self.dispatch = dispatch
        self.node = node
        self.exc_copy: Optional[Block] = None   # memoized exceptional copy


class _Builder:
    def __init__(self, func: ast.AST):
        self.cfg = CFG(func)
        self._n = 0
        self.cfg.entry = self.new_block("entry")
        self.cfg.exit = self.new_block("exit")
        self.cfg.exc_exit = self.new_block("exc-exit")
        #: (head, after, frame-depth) per enclosing loop.
        self.loops: List[Tuple[Block, Block, int]] = []

    def new_block(self, label: str = "") -> Block:
        b = Block(self._n, label)
        self._n += 1
        self.cfg.blocks.append(b)
        return b

    # -- exception routing ----------------------------------------------------

    def exc_entry(self, frames: List[_Frame]) -> Block:
        """Where an exception goes from under ``frames``: the innermost
        dispatch, running any intervening ``finally`` copies on the way."""
        if not frames:
            return self.cfg.exc_exit
        top, rest = frames[-1], frames[:-1]
        if top.kind == "dispatch":
            return top.dispatch
        if top.exc_copy is None:
            # Exceptional finally copy: runs the finalbody, then re-raises
            # outward.  Built once per frame no matter how many blocks raise
            # under it.
            entry = self.new_block("finally-exc")
            tail = self.build_stmts(top.node.finalbody, entry, rest)
            if tail is not None:
                tail.edge(self.exc_entry(rest), "exc")
            top.exc_copy = entry
        return top.exc_copy

    def _finally_chain(self, frames: List[_Frame], upto: int,
                       target: Block) -> Block:
        """Entry block of the chain of finally copies an abrupt exit (return
        / break / continue) runs while unwinding ``frames[upto:]`` down to
        ``target``."""
        for i in range(upto, len(frames)):
            f = frames[i]
            if f.kind != "finally":
                continue
            entry = self.new_block("finally-abrupt")
            tail = self.build_stmts(f.node.finalbody, entry, frames[:i])
            if tail is not None:
                tail.edge(target, "finally")
            target = entry
        return target

    # -- statement building ---------------------------------------------------

    def append(self, block: Block, stmt: ast.AST,
               frames: List[_Frame]) -> None:
        block.stmts.append(stmt)
        raising = may_raise(stmt)
        block.raising.append(raising)
        self.cfg.block_of.setdefault(id(stmt), block)
        if raising:
            block.edge(self.exc_entry(frames), "exc")

    def build_stmts(self, stmts: List[ast.stmt], block: Block,
                    frames: List[_Frame]) -> Optional[Block]:
        """Build ``stmts`` starting in ``block``; returns the open block the
        sequence falls out of, or None when control cannot fall through."""
        for stmt in stmts:
            if block is None:
                block = self.new_block("unreachable")
            block = self.build_stmt(stmt, block, frames)
        return block

    def build_stmt(self, stmt: ast.stmt, block: Block,
                   frames: List[_Frame]) -> Optional[Block]:
        if isinstance(stmt, ast.Return):
            self.append(block, stmt, frames)
            block.edge(self._finally_chain(frames, 0, self.cfg.exit),
                       "return")
            return None
        if isinstance(stmt, ast.Raise):
            self.append(block, stmt, frames)
            # append() already added the exc edge; re-label for readers.
            return None
        if isinstance(stmt, (ast.Break, ast.Continue)):
            if not self.loops:
                return block  # malformed; keep going
            head, after, depth = self.loops[-1]
            target = after if isinstance(stmt, ast.Break) else head
            self.cfg.block_of.setdefault(id(stmt), block)
            block.stmts.append(stmt)
            block.raising.append(False)
            kind = "break" if isinstance(stmt, ast.Break) else "continue"
            block.edge(self._finally_chain(frames, depth, target), kind)
            return None
        if isinstance(stmt, ast.If):
            self.append(block, stmt, frames)
            after = self.new_block("after-if")
            then_entry = self.new_block("then")
            block.edge(then_entry, "true")
            then_end = self.build_stmts(stmt.body, then_entry, frames)
            if then_end is not None:
                then_end.edge(after, "fall")
            if stmt.orelse:
                else_entry = self.new_block("else")
                block.edge(else_entry, "false")
                else_end = self.build_stmts(stmt.orelse, else_entry, frames)
                if else_end is not None:
                    else_end.edge(after, "fall")
            else:
                block.edge(after, "false")
            return after if after.preds else None
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._build_loop(stmt, block, frames)
        if isinstance(stmt, ast.Try):
            return self._build_try(stmt, block, frames)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.append(block, stmt, frames)
            return self.build_stmts(stmt.body, block, frames)
        if isinstance(stmt, ast.Match):
            self.append(block, stmt, frames)
            after = self.new_block("after-match")
            for case in stmt.cases:
                entry = self.new_block("case")
                block.edge(entry, "case")
                end = self.build_stmts(case.body, entry, frames)
                if end is not None:
                    end.edge(after, "fall")
            block.edge(after, "false")   # no case may match
            return after
        # Straight-line statement (incl. nested def/class: defining only).
        self.append(block, stmt, frames)
        return block

    def _build_loop(self, stmt: ast.stmt, block: Block,
                    frames: List[_Frame]) -> Optional[Block]:
        head = self.new_block("loop-head")
        block.edge(head, "fall")
        self.append(head, stmt, frames)
        after = self.new_block("after-loop")
        body_entry = self.new_block("loop-body")
        head.edge(body_entry, "true")
        self.loops.append((head, after, len(frames)))
        body_end = self.build_stmts(stmt.body, body_entry, frames)
        self.loops.pop()
        if body_end is not None:
            body_end.edge(head, "loop")
        test = getattr(stmt, "test", None)
        infinite = (isinstance(stmt, ast.While)
                    and isinstance(test, ast.Constant) and bool(test.value))
        if stmt.orelse and not infinite:
            else_entry = self.new_block("loop-else")
            head.edge(else_entry, "false")
            else_end = self.build_stmts(stmt.orelse, else_entry, frames)
            if else_end is not None:
                else_end.edge(after, "fall")
        elif not infinite:
            head.edge(after, "false")
        return after if after.preds else None

    def _build_try(self, stmt: ast.Try, block: Block,
                   frames: List[_Frame]) -> Optional[Block]:
        after = self.new_block("after-try")
        outer = list(frames)
        body_frames = list(frames)
        fin_frame: Optional[_Frame] = None
        if stmt.finalbody:
            fin_frame = _Frame("finally", node=stmt)
            body_frames.append(fin_frame)
        handler_frames = list(body_frames)   # handler bodies: own try inactive
        dispatch: Optional[Block] = None
        if stmt.handlers:
            dispatch = self.new_block("dispatch")
            body_frames.append(_Frame("dispatch", dispatch=dispatch))

        body_entry = self.new_block("try")
        block.edge(body_entry, "fall")
        body_end = self.build_stmts(stmt.body, body_entry, body_frames)
        if body_end is not None and stmt.orelse:
            # else runs only on clean body completion; its exceptions bypass
            # the handlers (handler_frames, not body_frames).
            body_end = self.build_stmts(stmt.orelse, body_end, handler_frames)

        ends: List[Block] = [b for b in (body_end,) if b is not None]
        if dispatch is not None:
            for h in stmt.handlers:
                entry = self.new_block("except")
                dispatch.edge(entry, "except")
                dispatch.handlers.append((h, entry))
                self.append(entry, h, handler_frames)
                h_end = self.build_stmts(h.body, entry, handler_frames)
                if h_end is not None:
                    ends.append(h_end)
            if not _catches_all(stmt.handlers):
                dispatch.edge(self.exc_entry(handler_frames), "exc")

        if stmt.finalbody:
            # Normal-path copy: one shared copy from every clean end.
            fin_entry = self.new_block("finally")
            fin_end = self.build_stmts(stmt.finalbody, fin_entry, outer)
            if fin_end is not None:
                fin_end.edge(after, "fall")
            for e in ends:
                e.edge(fin_entry, "finally")
        else:
            for e in ends:
                e.edge(after, "fall")
        return after if after.preds else None


def build_cfg(func: ast.AST) -> CFG:
    """CFG for one FunctionDef/AsyncFunctionDef body.  Prefer
    ``FileContext.cfg(func)`` -- it memoizes per node."""
    global BUILD_COUNT
    BUILD_COUNT += 1
    b = _Builder(func)
    body = func.body if isinstance(func, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) else [func]
    end = b.build_stmts(list(body), b.cfg.entry, [])
    if end is not None:
        end.edge(b.cfg.exit, "fall")
    return b.cfg


def functions_in(tree: ast.AST) -> List[ast.AST]:
    """Every (possibly nested) function definition in a module tree."""
    return [n for n in walk_fast(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
