"""Whole-program context for interprocedural passes (TJA010+).

Per-file passes (TJA001-TJA009) see one ``FileContext`` at a time; the
operator's hardest contracts are *cross-file*: env injected in
``controller/pod.py`` and read in ``workloads/``, event reasons registered in
``api/constants.py`` and emitted controller-wide, locks acquired across mixin
boundaries.  ``ProjectContext`` is built **once per run** from the per-file
ASTs the runner already parsed (no second parse, no I/O beyond the file walk
that already happened), so the whole-program layer stays in the same
milliseconds budget as the per-file layer.

What it provides:

- a **module symbol table**: dotted module name -> top-level classes (with
  their methods, base names, lock-creating attributes, and inferred
  ``self._x = ClassName(...)`` attribute types), functions, imports, and
  string constants;
- an **import graph** (project-internal edges only), so checks can resolve
  ``constants.FOO`` / ``from x import y`` references to their definitions;
- a **method-level call/lock summary** per function and method: which lock
  attributes it acquires, which callables it may call, and which calls and
  nested acquisitions happen *while a lock is held* -- the raw material for
  the TJA010 lock-order graph;
- resolution helpers: base-class lookup across modules, a flattened
  mixin-aware method table (``mro_methods``), and class-attribute enum
  reading (``class_string_attrs``, used to decode ``TrainingJobPhase.X``).

Everything is a conservative, syntactic approximation: dynamic dispatch,
monkey-patching and reflection are invisible.  That is the right trade for a
pre-test lint -- the passes built on top only report what they can witness
in the AST, and waivers cover the rest.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import FileContext, _TOKEN_NODES

#: threading factories whose assignment makes an attribute "a lock".
LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: Constructor leaf names whose module-level assignment creates a mutable
#: container singleton (the TJA027 inventory universe, alongside displays
#: and project-class constructions).
MUTABLE_CONTAINER_CTORS = {
    "dict", "list", "set", "bytearray", "deque", "defaultdict",
    "Counter", "OrderedDict", "ChainMap",
}

#: Lock factories that are reentrant: a self-cycle on one is legal.
REENTRANT_FACTORIES = {"RLock", "Condition"}


def _self_attr(node: ast.expr) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _lock_factory_name(value: ast.expr) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when ``value`` is a call to one."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name if name in LOCK_FACTORIES else None


def _mutable_kind(value: ast.expr) -> Optional[str]:
    """Container kind ("dict"/"list"/...) when ``value`` constructs a
    mutable container, "count" for ``itertools.count()``, else None."""
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(value, ast.Call):
        dotted = _dotted(value.func)
        if dotted is None:
            return None
        leaf = dotted.rpartition(".")[2]
        if leaf in MUTABLE_CONTAINER_CTORS:
            return leaf
        if leaf == "count" and dotted in ("count", "itertools.count"):
            return "count"
    return None


def module_name_for(rel_path: str) -> Optional[str]:
    """Dotted module name for a repo-relative ``.py`` path."""
    if not rel_path.endswith(".py"):
        return None
    parts = rel_path[:-3].split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts:
        return None
    return ".".join(parts)


@dataclass
class MethodSummary:
    """Call/lock facts for one function or method body."""
    qual: str                               # "pkg.mod.Class.method" / "pkg.mod.fn"
    node: ast.AST = None
    #: lock attribute names acquired directly (``with self.X:`` / ``X.acquire()``).
    acquires: Set[str] = field(default_factory=set)
    #: raw callee expressions seen anywhere: ("self", name) | ("name", name)
    #: | ("attr", recv_leaf, name) -- resolved lazily by the checks.
    calls: List[tuple] = field(default_factory=list)
    #: (held lock attr, callee tuple) for calls made while a lock is held.
    held_calls: List[tuple] = field(default_factory=list)
    #: (outer lock attr, inner lock attr, lineno) for directly nested acquires.
    nested_acquires: List[Tuple[str, str, int]] = field(default_factory=list)
    #: lock attr -> first acquisition lineno (for findings).
    acquire_lines: Dict[str, int] = field(default_factory=dict)


@dataclass
class ClassInfo:
    name: str
    module: str
    node: ast.ClassDef = None
    qual: str = ""                          # "pkg.mod.Class"
    bases: List[str] = field(default_factory=list)   # raw (possibly dotted) names
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    #: attr name -> lock factory kind, for attrs assigned a Lock()/RLock()/...
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    #: attr name -> raw class-name string from ``self._x = ClassName(...)``.
    attr_ctors: Dict[str, str] = field(default_factory=dict)
    #: attr name -> string value, for simple ``NAME = "str"`` class attributes.
    string_attrs: Dict[str, str] = field(default_factory=dict)
    summaries: Dict[str, MethodSummary] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    name: str
    ctx: FileContext = None
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    functions: Dict[str, ast.AST] = field(default_factory=dict)
    fn_summaries: Dict[str, MethodSummary] = field(default_factory=dict)
    #: local alias -> dotted target ("pkg.api.constants", "pkg.mod.fn").
    imports: Dict[str, str] = field(default_factory=dict)
    #: top-level NAME = "literal" string assignments.
    constants: Dict[str, str] = field(default_factory=dict)
    #: module-level lock names -> factory kind.
    module_locks: Dict[str, str] = field(default_factory=dict)
    #: module-level singletons: NAME -> raw class-name string from
    #: ``NAME = ClassName(...)`` (e.g. ``METRICS = MetricsRegistry()``).
    global_ctors: Dict[str, str] = field(default_factory=dict)
    #: module-level mutable containers: NAME -> (kind, lineno) for dict/
    #: list/set displays and comprehensions, builtin/collections container
    #: constructors, and ``itertools.count()`` counters.  The raw material
    #: for the TJA027 shard-state inventory; lock factories are excluded
    #: (they live in ``module_locks``).
    global_mutables: Dict[str, Tuple[str, int]] = field(default_factory=dict)


def _dotted(node: ast.expr) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class CallResolver:
    """Resolution over the summary call/lock graph, with caches.

    Grew up inside the TJA010 lock-order pass; promoted here once the
    thread-model layer (tools/analyze/threadmodel.py) needed the same
    callee/lock resolution to build role closures -- one resolver, one
    set of caches, shared by every consumer of ``MethodSummary.calls``.
    """

    def __init__(self, pc: "ProjectContext"):
        self.pc = pc
        self._composites: Dict[str, List[ClassInfo]] = {}
        self._creator: Dict[Tuple[str, str], Optional[Tuple[str, str]]] = {}

    def composites(self, ci: ClassInfo) -> List[ClassInfo]:
        got = self._composites.get(ci.qual)
        if got is None:
            got = self.pc.subclasses_including(ci)
            self._composites[ci.qual] = got
        return got

    def lock_id(self, mod: ModuleInfo, cls: Optional[ClassInfo],
                name: str) -> Optional[Tuple[str, str]]:
        """(lock id, factory kind) for a raw acquisition name recorded in a
        summary: a module-level lock, or a ``self.X`` attribute whose
        creating class is found in the MRO of any composite the defining
        class is mixed into.  None when the name is not provably a lock."""
        if name in mod.module_locks:
            return f"{mod.name}.{name}", mod.module_locks[name]
        if cls is None:
            return None
        key = (cls.qual, name)
        if key in self._creator:
            return self._creator[key]
        found: Optional[Tuple[str, str]] = None
        for k in [cls] + self.composites(cls):
            for c in self.pc.mro_classes(k):
                if name in c.lock_attrs:
                    found = (f"{c.qual}.{name}", c.lock_attrs[name])
                    break
            if found:
                break
        self._creator[key] = found
        return found

    def callee_summaries(self, mod: ModuleInfo, cls: Optional[ClassInfo],
                         callee: tuple) -> List[Tuple[ModuleInfo,
                                                      Optional[ClassInfo],
                                                      MethodSummary]]:
        kind = callee[0]
        out: List[Tuple[ModuleInfo, Optional[ClassInfo], MethodSummary]] = []
        if kind == "self" and cls is not None:
            name = callee[1]
            seen: Set[str] = set()
            for k in self.composites(cls):
                table = self.pc.mro_methods(k)
                hit = table.get(name)
                if hit is None:
                    continue
                ci, _node = hit
                s = ci.summaries.get(name)
                if s is not None and s.qual not in seen:
                    seen.add(s.qual)
                    out.append((self.pc.modules[ci.module], ci, s))
            return out
        if kind == "name":
            name = callee[1]
            if name in mod.fn_summaries:
                return [(mod, None, mod.fn_summaries[name])]
            target = mod.imports.get(name)
            if target:
                tmod, _, leaf = target.rpartition(".")
                mi = self.pc.modules.get(tmod)
                if mi is not None and leaf in mi.fn_summaries:
                    return [(mi, None, mi.fn_summaries[leaf])]
            return out
        if kind == "attr":
            leaf, meth = callee[1], callee[2]
            ctor: Optional[Tuple[str, str]] = None   # (module, class name)
            if cls is not None:
                for k in [cls] + self.composites(cls):
                    for c in self.pc.mro_classes(k):
                        if leaf in c.attr_ctors:
                            ctor = (c.module, c.attr_ctors[leaf])
                            break
                    if ctor:
                        break
            if ctor is None:
                tgt, src_mod = mod.global_ctors.get(leaf), mod.name
                if tgt is None:
                    imp = mod.imports.get(leaf)
                    if imp:
                        m, _, l2 = imp.rpartition(".")
                        mi = self.pc.modules.get(m)
                        if mi is not None and l2 in mi.global_ctors:
                            tgt, src_mod = mi.global_ctors[l2], m
                if tgt is not None:
                    ctor = (src_mod, tgt)
            if ctor is not None:
                ci = self.pc.resolve_class(ctor[0], ctor[1])
                if ci is not None:
                    table = self.pc.mro_methods(ci)
                    hit = table.get(meth)
                    if hit is not None:
                        c2, _node = hit
                        s = c2.summaries.get(meth)
                        if s is not None:
                            out.append((self.pc.modules[c2.module], c2, s))
            return out
        return out


#: Node classes with no walk-relevant descendants (their only children are
#: ctx/operator tokens); the body walker returns without recursing.
_WALK_LEAVES = frozenset({
    ast.Name, ast.Constant, ast.Pass, ast.Break, ast.Continue,
    ast.Load, ast.Store, ast.Del, ast.alias,
})

#: Leaves plus the grammar-token singletons (operators, comparators, ...):
#: visiting any of these is a guaranteed no-op, so the child loop skips the
#: dispatch call entirely -- they are ~60% of all child visits.
_WALK_SKIP = _WALK_LEAVES | _TOKEN_NODES


class _BodyWalker:
    """One pass over a function body collecting the MethodSummary facts,
    tracking the stack of currently-held lock attributes."""

    def __init__(self, summary: MethodSummary, lock_attrs: Set[str],
                 module_locks: Set[str]):
        self.s = summary
        self.lock_attrs = lock_attrs
        self.module_locks = module_locks

    def _lock_of(self, expr: ast.expr) -> Optional[str]:
        """Lock attr/name candidate for a ``with`` item.  Any plain
        ``with self.X:`` is recorded (the lock may be *created* in a sibling
        mixin this walker can't see; checks filter against the composed
        class's MRO).  Bare names and call-wrapped forms must name a known
        lock."""
        attr = _self_attr(expr)
        if attr is not None:
            return attr
        if isinstance(expr, ast.Name):
            return expr.id if expr.id in self.module_locks else None
        if isinstance(expr, ast.Call):
            fn = expr.func
            if isinstance(fn, ast.Attribute):
                attr = _self_attr(fn.value)
                if attr is None and isinstance(fn.value, ast.Name) \
                        and fn.value.id in self.module_locks:
                    attr = fn.value.id
                if attr is not None and (attr in self.lock_attrs
                                         or attr in self.module_locks):
                    return attr
        return None

    def _callee(self, call: ast.Call) -> Optional[tuple]:
        fn = call.func
        if isinstance(fn, ast.Name):
            return ("name", fn.id)
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                return ("self", fn.attr)
            leaf = recv.id if isinstance(recv, ast.Name) else (
                _self_attr(recv) or (recv.attr if isinstance(recv, ast.Attribute)
                                     else None))
            if leaf is not None:
                return ("attr", leaf, fn.attr)
        return None

    def _record_acquire(self, lock: str, lineno: int, held: List[str]) -> None:
        self.s.acquires.add(lock)
        self.s.acquire_lines.setdefault(lock, lineno)
        for outer in held:
            if outer != lock:
                self.s.nested_acquires.append((outer, lock, lineno))

    def _record_call(self, call: ast.Call, held: List[str]) -> None:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
            lock = self._lock_of(fn.value)
            if lock is not None:
                self._record_acquire(lock, call.lineno, held)
        callee = self._callee(call)
        if callee is not None:
            self.s.calls.append(callee + (call.lineno,))
            for lock in held:
                self.s.held_calls.append((lock, callee, call.lineno))

    def walk(self, node: ast.AST, held: List[str]) -> None:
        """Visit every descendant of ``node`` (not ``node`` itself),
        maintaining the stack of held locks through ``with`` blocks.
        Child enumeration is inlined (same trick as FileContext._build_walk):
        iter_child_nodes/iter_fields generator resumptions over every method
        body in the tree are a visible slice of the lint budget."""
        visit = self.visit
        isinst, AST, skip = isinstance, ast.AST, _WALK_SKIP
        d = node.__dict__
        for name in node._fields:
            v = d.get(name)
            if v.__class__ is list:
                for item in v:
                    if item.__class__ not in skip and isinst(item, AST):
                        visit(item, held)
            elif v.__class__ not in skip and isinst(v, AST):
                visit(v, held)

    def visit(self, node: ast.AST, held: List[str]) -> None:
        # Dispatch on exact class identity: ast node classes are never
        # subclassed here, and this method runs once per node of every
        # function body in the tree -- three isinstance tuple sieves per
        # node were a measurable slice of the lint budget.
        cls = node.__class__
        if cls in _WALK_LEAVES:
            # Childless (or child-irrelevant) nodes: recursing further only
            # enumerates ctx/operator tokens.
            return
        if cls is ast.Call:
            self._record_call(node, held)
        elif cls is ast.With or cls is ast.AsyncWith:
            inner = list(held)
            for item in node.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self._record_acquire(lock, node.lineno, inner)
                    inner = inner + [lock]
                else:
                    if isinstance(item.context_expr, ast.Call):
                        self._record_call(item.context_expr, held)
                    self.walk(item.context_expr, held)
            for stmt in node.body:
                self.visit(stmt, inner)
            return
        elif (cls is ast.FunctionDef or cls is ast.AsyncFunctionDef
                or cls is ast.Lambda):
            # A nested def/lambda is a deferred execution context (gauge
            # callbacks, thread targets): it runs when *invoked*, not here,
            # so neither its acquisitions nor its calls belong in this
            # summary -- attributing them poisons the enclosing method's
            # may-acquire set with scrape-time work.
            return
        self.walk(node, held)


class ProjectContext:
    """The whole analyzed tree, cross-referenced.  Built once per run."""

    def __init__(self, root: str):
        self.root = root
        self.files: Dict[str, FileContext] = {}
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}    # qual -> info
        self._mro_cache: Dict[str, Dict[str, Tuple[ClassInfo, ast.AST]]] = {}
        self._subclass_map: Optional[Dict[str, List[ClassInfo]]] = None
        self._mro_classes_cache: Dict[str, List[ClassInfo]] = {}
        self._covers: Dict[str, bool] = {}

    # -- construction --------------------------------------------------------

    @classmethod
    def build(cls, root: str, contexts: Dict[str, FileContext]) -> "ProjectContext":
        pc = cls(root)
        pc.files = dict(contexts)
        for rel, ctx in contexts.items():
            if ctx.tree is None:
                continue
            mod = module_name_for(rel)
            if mod is None:
                continue
            pc.modules[mod] = pc._index_module(mod, ctx)
        for info in pc.modules.values():
            for ci in info.classes.values():
                pc.classes[ci.qual] = ci
        return pc

    def _index_module(self, mod: str, ctx: FileContext) -> ModuleInfo:
        info = ModuleInfo(name=mod, ctx=ctx)
        for node in ctx.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    info.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    prefix = mod.split(".")
                    # level 1 = current package for a module file.
                    prefix = prefix[:-node.level]
                    base = ".".join(prefix + ([base] if base else []))
                for alias in node.names:
                    info.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    info.constants[name] = node.value.value
                kind = _lock_factory_name(node.value)
                if kind is not None:
                    info.module_locks[name] = kind
                else:
                    mut = _mutable_kind(node.value)
                    if mut is not None:
                        info.global_mutables[name] = (mut, node.lineno)
                    if isinstance(node.value, ast.Call):
                        ctor = _dotted(node.value.func)
                        if ctor is not None:
                            info.global_ctors[name] = ctor
            elif isinstance(node, ast.AnnAssign) and node.value is not None \
                    and isinstance(node.target, ast.Name):
                mut = _mutable_kind(node.value)
                if mut is not None:
                    info.global_mutables[node.target.id] = (mut, node.lineno)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                ci = self._index_class(mod, node)
                info.classes[node.name] = ci
        # Self-attribute inference (lock attrs + attr constructors): one
        # sweep over the file's cached Assign bucket attributed to the
        # enclosing top-level class via the parents map.  An ast.walk per
        # method body here was a visible slice of the lint budget.
        by_node = {id(ci.node): ci for ci in info.classes.values()}
        parents = ctx.parents
        for sub in ctx.by_type(ast.Assign):
            kind = _lock_factory_name(sub.value)
            ctor = None
            if kind is None:
                if isinstance(sub.value, ast.Call):
                    ctor = _dotted(sub.value.func)
                if ctor is None:
                    continue
            attrs = [a for a in (_self_attr(t) for t in sub.targets)
                     if a is not None]
            if not attrs:
                continue
            anc = parents.get(id(sub))
            owner = None
            while anc is not None:
                if isinstance(anc, ast.ClassDef):
                    owner = by_node.get(id(anc))
                    if owner is not None:
                        break
                anc = parents.get(id(anc))
            if owner is None:
                continue
            for attr in attrs:
                if kind is not None:
                    owner.lock_attrs[attr] = kind
                else:
                    owner.attr_ctors.setdefault(attr, ctor)
        # Summaries need the full lock attr/module-lock sets, so second pass.
        for ci in info.classes.values():
            lock_names = set(ci.lock_attrs)
            for name, m in ci.methods.items():
                s = MethodSummary(qual=f"{ci.qual}.{name}", node=m)
                _BodyWalker(s, lock_names, set(info.module_locks)).walk(m, [])
                ci.summaries[name] = s
        for name, fn in info.functions.items():
            s = MethodSummary(qual=f"{mod}.{name}", node=fn)
            _BodyWalker(s, set(), set(info.module_locks)).walk(fn, [])
            info.fn_summaries[name] = s
        return info

    def _index_class(self, mod: str, node: ast.ClassDef) -> ClassInfo:
        ci = ClassInfo(name=node.name, module=mod, node=node,
                       qual=f"{mod}.{node.name}")
        for b in node.bases:
            d = _dotted(b)
            if d is not None:
                ci.bases.append(d)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[item.name] = item
                # lock_attrs/attr_ctors are filled by _index_module's single
                # file-level Assign sweep (parents-attributed).
            elif isinstance(item, ast.Assign) and len(item.targets) == 1 \
                    and isinstance(item.targets[0], ast.Name) \
                    and isinstance(item.value, ast.Constant) \
                    and isinstance(item.value.value, str):
                ci.string_attrs[item.targets[0].id] = item.value.value
        return ci

    # -- resolution ----------------------------------------------------------

    def resolve_module(self, from_mod: str, alias: str) -> Optional[ModuleInfo]:
        """ModuleInfo for a local name (``constants`` after
        ``from ..api import constants``)."""
        info = self.modules.get(from_mod)
        if info is None:
            return None
        target = info.imports.get(alias, alias)
        return self.modules.get(target)

    def resolve_class(self, from_mod: str, name: str) -> Optional[ClassInfo]:
        """ClassInfo for a (possibly dotted) class name as written in
        ``from_mod``."""
        info = self.modules.get(from_mod)
        if info is None:
            return None
        if "." in name:
            head, _, rest = name.partition(".")
            target = info.imports.get(head, head)
            cand = self.classes.get(f"{target}.{rest}")
            if cand is not None:
                return cand
            sub = self.modules.get(f"{target}")
            if sub is not None and rest in sub.classes:
                return sub.classes[rest]
            return self.classes.get(f"{head}.{rest}")
        if name in info.classes:
            return info.classes[name]
        target = info.imports.get(name)
        if target is not None:
            mod, _, cls_name = target.rpartition(".")
            sub = self.modules.get(mod)
            if sub is not None and cls_name in sub.classes:
                return sub.classes[cls_name]
        return None

    def mro_methods(self, ci: ClassInfo) -> Dict[str, Tuple[ClassInfo, ast.AST]]:
        """Flattened method table: name -> (defining class, node), walking
        bases left-to-right depth-first (Python's MRO for the simple
        mixin-composition shapes this codebase uses)."""
        cached = self._mro_cache.get(ci.qual)
        if cached is not None:
            return cached
        table: Dict[str, Tuple[ClassInfo, ast.AST]] = {}
        seen: Set[str] = set()

        def visit(c: ClassInfo) -> None:
            if c.qual in seen:
                return
            seen.add(c.qual)
            for name, node in c.methods.items():
                table.setdefault(name, (c, node))
            for b in c.bases:
                base = self.resolve_class(c.module, b)
                if base is not None:
                    visit(base)

        visit(ci)
        self._mro_cache[ci.qual] = table
        return table

    def mro_classes(self, ci: ClassInfo) -> List[ClassInfo]:
        cached = self._mro_classes_cache.get(ci.qual)
        if cached is not None:
            return cached
        out: List[ClassInfo] = []
        seen: Set[str] = set()

        def visit(c: ClassInfo) -> None:
            if c.qual in seen:
                return
            seen.add(c.qual)
            out.append(c)
            for b in c.bases:
                base = self.resolve_class(c.module, b)
                if base is not None:
                    visit(base)

        visit(ci)
        self._mro_classes_cache[ci.qual] = out
        return out

    def subclasses_including(self, ci: ClassInfo) -> List[ClassInfo]:
        """Every class whose MRO contains ``ci`` (including itself) -- the
        instance shapes a ``self.X`` access may run under."""
        if self._subclass_map is None:
            # One sweep inverting every class's MRO beats re-scanning all
            # classes per query (callers hit this for every lock and call).
            inv: Dict[str, List[ClassInfo]] = {}
            for other in self.classes.values():
                for c in self.mro_classes(other):
                    inv.setdefault(c.qual, []).append(other)
            self._subclass_map = inv
        return list(self._subclass_map.get(ci.qual, []))

    def class_string_attrs(self, from_mod: str, name: str) -> Dict[str, str]:
        """``NAME -> "value"`` class attributes for an enum-style class as
        referenced from ``from_mod`` (e.g. ``TrainingJobPhase``)."""
        ci = self.resolve_class(from_mod, name)
        return dict(ci.string_attrs) if ci is not None else {}

    def module_of_path(self, rel_path: str) -> Optional[ModuleInfo]:
        mod = module_name_for(rel_path)
        return self.modules.get(mod) if mod else None

    def covers_package(self, prefix: str) -> bool:
        """True when every ``.py`` file on disk under ``prefix`` (repo-
        relative directory) is in the analyzed set.  Absence-based passes
        ("nothing reads X") gate on this so a single-file run doesn't turn
        partial visibility into false whole-program claims."""
        cached = self._covers.get(prefix)
        if cached is not None:
            return cached
        base = os.path.join(self.root, prefix.replace("/", os.sep))
        ok = os.path.isdir(base)
        if ok:
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__" and not d.startswith(".")]
                for fn in filenames:
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root).replace(os.sep, "/")
                    if rel not in self.files:
                        ok = False
                        break
                if not ok:
                    break
        self._covers[prefix] = ok
        return ok

    def ensure_module(self, rel_path: str) -> Optional[ModuleInfo]:
        """ModuleInfo for a repo-relative path; when the file was not part of
        the analyzed set (a subset run like ``tools.analyze foo.py``), parse
        and index it from disk on demand so registry-backed checks still see
        ``api/constants.py`` / ``api/types.py``."""
        mod = module_name_for(rel_path)
        if mod is None:
            return None
        if mod in self.modules:
            return self.modules[mod]
        abs_path = os.path.join(self.root, rel_path.replace("/", os.sep))
        if not os.path.exists(abs_path):
            return None
        try:
            with open(abs_path, "r", encoding="utf-8", errors="replace") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=rel_path)
        except (OSError, SyntaxError):
            return None
        ctx = FileContext(path=rel_path, abs_path=abs_path, source=source,
                          lines=source.splitlines())
        ctx.tree = tree
        info = self._index_module(mod, ctx)
        self.modules[mod] = info
        for ci in info.classes.values():
            self.classes[ci.qual] = ci
        return info
