"""Incremental result cache for full analyzer runs.

The analyzer is a pure function of the analyzed files: same bytes in, same
findings out.  A full run over this tree costs most of the ``--max-seconds``
CI budget, and the common invocation (``make lint``) re-analyzes a tree that
has not changed since the last run.  So full runs memoize their findings on
disk keyed by a fingerprint of every analyzed file -- ``(relpath, size,
mtime_ns)`` per file, hashed -- plus the same triple for every file of the
analyzer package itself, so editing a check invalidates entries even when
``tools/`` is not among the analyzed roots (tests analyze temp trees).

Only the plain full-run shape is cached (no ``--checks`` subset, no
``--changed-since`` scoping, no baseline snapshot): those paths are either
already incremental or explicitly want a fresh run.  Findings are cached
*raw*, before baseline suppression and formatting, so baseline or format
changes take effect on warm hits.  The cache is best-effort: any read,
parse, or write failure silently degrades to a cold run.  ``--no-cache``
bypasses it entirely.

The cache file lives at ``<root>/.analyze-cache.json`` (gitignored), holds a
handful of entries (one per distinct root set, e.g. the real tree and test
temp trees), and is rewritten atomically.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Iterable, List, Optional

from tools.analyze.findings import Finding

#: Cache file name, relative to the analysis root.
CACHE_BASENAME = ".analyze-cache.json"

#: Schema version: bump when the entry layout changes.
_VERSION = 1

#: Entries kept per cache file (distinct analyzed root sets).
_MAX_ENTRIES = 8

_FIELDS = ("check_id", "check_name", "path", "line", "col", "severity",
           "message")


def _stat_line(rel: str, path: str) -> Optional[str]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return f"{rel}\x00{st.st_size}\x00{st.st_mtime_ns}"


def fingerprint(files: Iterable[str], root: str) -> str:
    """Hash of (relpath, size, mtime_ns) for every analyzed file plus every
    file of the analyzer package itself."""
    h = hashlib.sha256()
    lines: List[str] = []
    for path in files:
        line = _stat_line(os.path.relpath(path, root), path)
        if line is None:
            return ""          # racing deletion: don't cache this run
        lines.append(line)
    pkg = os.path.dirname(os.path.abspath(__file__))
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith((".py", ".json")):
                ap = os.path.join(dirpath, fn)
                line = _stat_line(os.path.relpath(ap, pkg), ap)
                if line is not None:
                    lines.append("@" + line)
    for line in sorted(lines):
        h.update(line.encode("utf-8", "replace"))
        h.update(b"\n")
    return h.hexdigest()


def _key(paths: List[str]) -> str:
    return hashlib.sha256(
        "\x00".join(sorted(paths)).encode("utf-8", "replace")).hexdigest()


def _cache_path(root: str) -> str:
    return os.path.join(root, CACHE_BASENAME)


def load(root: str, paths: List[str], fp: str) -> Optional[List[Finding]]:
    """Cached findings for this (root set, fingerprint), or None."""
    if not fp:
        return None
    try:
        with open(_cache_path(root), "r", encoding="utf-8") as fh:
            doc = json.load(fh)
        if doc.get("v") != _VERSION:
            return None
        entry = doc.get("entries", {}).get(_key(paths))
        if entry is None or entry.get("fp") != fp:
            return None
        return [Finding(**{f: row[i] for i, f in enumerate(_FIELDS)})
                for row in entry["findings"]]
    except (OSError, ValueError, KeyError, TypeError, IndexError):
        return None


def store(root: str, paths: List[str], fp: str,
          findings: List[Finding]) -> None:
    """Best-effort write-through; never raises."""
    if not fp:
        return
    path = _cache_path(root)
    try:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                doc = json.load(fh)
            if doc.get("v") != _VERSION or not isinstance(
                    doc.get("entries"), dict):
                doc = {"v": _VERSION, "entries": {}}
        except (OSError, ValueError):
            doc = {"v": _VERSION, "entries": {}}
        entries = doc["entries"]
        entries.pop(_key(paths), None)
        while len(entries) >= _MAX_ENTRIES:
            entries.pop(next(iter(entries)))
        entries[_key(paths)] = {
            "fp": fp,
            "findings": [[getattr(f, name) for name in _FIELDS]
                         for f in findings],
        }
        fd, tmp = tempfile.mkstemp(
            prefix=CACHE_BASENAME, dir=os.path.dirname(path) or ".")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, separators=(",", ":"))
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
    except OSError:
        pass
