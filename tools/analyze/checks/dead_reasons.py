"""TJA014 dead-event-reason: registry entries no emission site uses.

``api/constants.py`` declares every Kubernetes event reason in
``EVENT_REASONS`` and TJA007 proves each ``recorder.event(...)`` call uses
a registered reason -- but nothing proved the converse.  A registry entry
with no emission site is worse than dead code: operators write alert rules
and ``kubectl get events --field-selector reason=...`` filters against the
registry, and a dead entry means the alert can never fire.  The usual
cause is a feature whose emission site was refactored away (or never
landed) while the constant survived.

A reason counts as *used* when either:

- its literal value is passed to a recorder ``.event(...)`` call (same
  receiver heuristic as TJA007), directly or via the ``*_REASON`` constant
  naming it; or
- the ``*_REASON`` constant naming it is referenced as an attribute
  anywhere outside ``api/constants.py`` -- that covers dynamic flows like
  the ``PHASE_REASON`` phase->reason table in ``api/types.py`` and
  telemetry paths that pick reasons at runtime.

Unused members are reported at their line inside the ``EVENT_REASONS``
declaration.  "Nothing uses it" is a whole-package claim, so the pass is
inert unless the analyzed set covers the package.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import ModuleInfo, ProjectContext
from tools.analyze.runner import register_project

CONSTANTS_REL = "trainingjob_operator_tpu/api/constants.py"
REGISTRY_NAME = "EVENT_REASONS"


def _registry_members(const_mod: ModuleInfo) -> Dict[str, int]:
    """reason value -> line of its member inside the frozenset literal."""
    if const_mod.ctx is None or const_mod.ctx.tree is None:
        return {}
    for node in const_mod.ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == REGISTRY_NAME
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "frozenset" and node.value.args):
            continue
        seq = node.value.args[0]
        out: Dict[str, int] = {}
        if isinstance(seq, (ast.Tuple, ast.List, ast.Set)):
            for el in seq.elts:
                if isinstance(el, ast.Name) and el.id in const_mod.constants:
                    out[const_mod.constants[el.id]] = el.lineno
                elif isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out[el.value] = el.lineno
        return out
    return {}


def _used_reasons(pc: ProjectContext, const_mod: ModuleInfo) -> Set[str]:
    #: constant name -> reason value, for every ``*_REASON`` declaration.
    by_name = {n: v for n, v in const_mod.constants.items()
               if n.endswith("_REASON")}
    used: Set[str] = set()
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or rel == CONSTANTS_REL \
                or not rel.startswith("trainingjob_operator_tpu/"):
            continue
        for node in ctx.by_type(ast.Attribute, ast.Name, ast.Call):
            if isinstance(node, ast.Attribute) and node.attr in by_name:
                used.add(by_name[node.attr])
            elif isinstance(node, ast.Name) and node.id in by_name:
                # ``from ..api.constants import X_REASON`` then bare use.
                used.add(by_name[node.id])
            elif isinstance(node, ast.Call):
                fn = node.func
                if not (isinstance(fn, ast.Attribute) and fn.attr == "event"):
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        used.add(arg.value)
    return used


@register_project("TJA014", "dead-event-reason")
def check(pc: ProjectContext) -> List[Finding]:
    const_mod = pc.ensure_module(CONSTANTS_REL)
    if const_mod is None:
        return []
    members = _registry_members(const_mod)
    if not members:
        return []
    if not pc.covers_package("trainingjob_operator_tpu"):
        return []
    used = _used_reasons(pc, const_mod)
    findings: List[Finding] = []
    for value in sorted(set(members) - used):
        findings.append(Finding(
            "TJA014", "dead-event-reason", CONSTANTS_REL, members[value], 0,
            ERROR,
            f"event reason {value!r} is registered in EVENT_REASONS but no "
            "emission site ever passes it to a recorder; wire up the "
            "emission or delete the registry entry (alerts filtering on a "
            "dead reason can never fire)"))
    findings.sort(key=Finding.sort_key)
    return findings
