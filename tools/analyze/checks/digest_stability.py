"""TJA025 digest-stability: taint from nondeterminism sources to digests.

Everything the robustness gates compare byte-for-byte flows through a
small set of sinks: ``ChaosPlan.canonical()``/``digest()``
(fleet/chaos.py), the incident bundle's sorted-keys ``json.dumps``
(obs/incident.py), checkpoint footers' ``hashlib`` digests
(workloads/train.py).  A digest is only as reproducible as its inputs;
this pass tracks nondeterministic *values* -- wall clock, ``id()``,
default ``repr``, OS entropy, global-``random`` draws -- through local
assignment chains and project-function returns (determinism.py's
memoized fixpoint) and reports any that reach a digest sink:

- ``hashlib.sha256(...)``-family constructor arguments, and
  ``h.update(x)`` where ``h`` is a local hasher;
- ``json.dumps(..., sort_keys=True)`` arguments -- sorted keys launder
  dict *order*, not tainted values (and not list order: a list
  materialized from a set stays unstable, which is why unsorted-set
  materialization is also a source here);
- arguments to ``canonical()``/``digest()``/``hexdigest()`` methods
  (zero-argument calls digest ``self``, which attribute-level taint
  cannot witness -- the conservative trade the module docstring of
  determinism.py spells out).

Unlike TJA024 this pass is package-wide (tests excluded): a wall-clock
timestamp baked into a digest is a bug wherever it happens, not just in
the plan generators.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tools.analyze import determinism as det
from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project

CHECK_ID, CHECK_NAME = "TJA025", "digest-stability"


def _sink_of(mod, rec, call: ast.Call) -> Optional[Tuple[str, List[ast.expr]]]:
    """(sink label, argument exprs to vet) when ``call`` is a digest sink."""
    fn = call.func
    canon = det.canonical_callee(mod, fn)
    if canon in det.HASHLIB_CTORS:
        return (canon, list(call.args))
    if isinstance(fn, ast.Attribute):
        if (fn.attr == "update" and isinstance(fn.value, ast.Name)
                and rec is not None and fn.value.id in rec.hasher_names):
            return (f"{fn.value.id}.update", list(call.args))
        if fn.attr in det.DIGEST_METHODS and call.args:
            return (f".{fn.attr}()", list(call.args))
    if canon == "json.dumps":
        for kw in call.keywords:
            if (kw.arg == "sort_keys"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True):
                return ("json.dumps(sort_keys=True)", list(call.args))
    return None


def _order_witness(mod, rec, df, expr: ast.expr) -> Optional[Tuple[str, int]]:
    """A set-typed value materialized into the sink without ``sorted()``:
    its element order is hash-randomization-dependent."""
    for node in det.walk_fast(expr):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "sorted"):
            return None   # conservatively treat a sorted() wrap as laundering
    for node in det.walk_fast(expr):
        if det.is_set_expr(mod, rec, node, df) and not isinstance(
                node, ast.BinOp):
            return ("unsorted set materialization", node.lineno)
    return None


#: Attribute leaves that make a call a sink *candidate* -- the cheap
#: pre-filter that keeps this pass from resolving the enclosing function
#: (and computing its taint set) for the ~99% of calls that digest nothing.
_SINK_LEAVES = det.DIGEST_METHODS | {"update", "dumps", "new"} | {
    name.rpartition(".")[2] for name in det.HASHLIB_CTORS}


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    df = det.facts(pc)
    findings: List[Finding] = []
    for rel in sorted(df.by_path):
        ctx = pc.files.get(rel)
        mod = pc.module_of_path(rel)
        if ctx is None or mod is None:
            continue
        by_fn = {id(rec.node): rec for rec in df.by_path[rel]}
        taints = {}   # id(rec.node) -> its local value-taint set
        parents = ctx.parents
        for call in ctx.by_type(ast.Call):
            fn = call.func
            leaf = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if leaf not in _SINK_LEAVES:
                continue
            rec = None
            anc = parents.get(id(call))
            while anc is not None:
                rec = by_fn.get(id(anc))
                if rec is not None:
                    break
                anc = parents.get(id(anc))
            sink = _sink_of(mod, rec, call)
            if sink is None:
                continue
            if rec is not None:
                vt = taints.get(id(rec.node))
                if vt is None:
                    vt = taints[id(rec.node)] = \
                        det.local_value_taint(mod, rec, df)
            else:
                vt = set()
            label, args = sink
            for arg in args:
                witness = det._expr_source(mod, rec, arg, vt, df) \
                    or _order_witness(mod, rec, df, arg)
                if witness is not None:
                    kind, line = witness
                    findings.append(Finding(
                        CHECK_ID, CHECK_NAME, rel, call.lineno,
                        call.col_offset, ERROR,
                        f"{kind} (line {line}) reaches digest sink "
                        f"{label}: same-input runs will not reproduce "
                        "byte-identical digests; feed the sink "
                        "deterministic values (seeded draws, threaded "
                        "clocks, sorted materializations) instead"))
                    break
    findings.sort(key=Finding.sort_key)
    return findings
