"""TJA013 phase-transition-exhaustiveness: the phase machine vs its
declared legal-transition table.

The job phase state machine is spread across ``controller/status.py`` (the
``update_job_conditions`` helper and the update_status flow) and the
reconcile loop -- nothing ever said which transitions are *legal*, so a new
code path can quietly wire e.g. ``Succeed -> Running`` and resurrect a
completed job.  ``api/constants.py`` now declares the table
(``PHASE_TRANSITIONS``: source phase -> allowed targets, spellings from
``api/types.py`` ``TrainingJobPhase``); this pass extracts the transition
graph the code actually implements and diffs the two:

- every ``update_job_conditions(job, TARGET, ...)`` call site's target must
  be a phase the table allows *some* source to reach (unknown targets are
  typos or undeclared machine growth);
- when the call site is dominated by a positive phase test -- an ancestor
  ``if`` comparing ``<job>.status.phase == TrainingJobPhase.X`` (or
  ``in (X, Y)``) in the taken branch -- the witnessed ``(X, TARGET)`` pair
  must be in the table.  Negative tests (``!=`` / ``not in``) and
  un-tested call sites constrain nothing.

``TrainingJobPhase.X`` attributes are decoded through the project symbol
table (``api/types.py``), so ``PodPhase`` comparisons never participate.
Same-phase refreshes are always legal.  Dynamic targets (variables like a
computed ``ending_phase``) are skipped -- the runtime ``is_job_completed``
guard owns those.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import ModuleInfo, ProjectContext
from tools.analyze.runner import register_project

CONSTANTS_REL = "trainingjob_operator_tpu/api/constants.py"
TYPES_REL = "trainingjob_operator_tpu/api/types.py"
TABLE_NAME = "PHASE_TRANSITIONS"
PHASE_CLASS = "TrainingJobPhase"
TRANSITION_FNS = {"update_job_conditions"}


def _load_table(const_mod: ModuleInfo) -> Dict[str, Set[str]]:
    if const_mod.ctx is None or const_mod.ctx.tree is None:
        return {}
    for node in const_mod.ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == TABLE_NAME
                and isinstance(node.value, ast.Dict)):
            continue
        table: Dict[str, Set[str]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                continue
            targets: Set[str] = set()
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                targets = {el.value for el in v.elts
                           if isinstance(el, ast.Constant)
                           and isinstance(el.value, str)}
            table[k.value] = targets
        return table
    return {}


def _phase_names(pc: ProjectContext) -> Dict[str, str]:
    """``TrainingJobPhase`` attribute name -> phase string value."""
    types_mod = pc.ensure_module(TYPES_REL)
    if types_mod is None:
        return {}
    ci = types_mod.classes.get(PHASE_CLASS)
    return dict(ci.string_attrs) if ci is not None else {}


def _phase_value(node: ast.expr, attr_to_value: Dict[str, str],
                 const_values: Dict[str, str]) -> Optional[str]:
    """The phase string an expression statically denotes, or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in const_values.values() else None
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Name) and base.id == PHASE_CLASS \
                and node.attr in attr_to_value:
            return attr_to_value[node.attr]
    return None


def _is_job_phase_expr(node: ast.expr) -> bool:
    """True for ``<something not pod-like>.status.phase``."""
    if not (isinstance(node, ast.Attribute) and node.attr == "phase"):
        return False
    status = node.value
    if not (isinstance(status, ast.Attribute) and status.attr == "status"):
        return False
    leaf = status.value
    name = leaf.id if isinstance(leaf, ast.Name) else (
        leaf.attr if isinstance(leaf, ast.Attribute) else "")
    return "pod" not in name.lower()


class _SourceSets(ast.NodeVisitor):
    """For every transition call site, the set of source phases witnessed by
    dominating positive ``.status.phase`` tests (None = unconstrained)."""

    def __init__(self, attr_to_value: Dict[str, str],
                 const_values: Dict[str, str]):
        self.attr_to_value = attr_to_value
        self.const_values = const_values
        self.stack: List[Set[str]] = []
        self.sites: List[Tuple[ast.Call, Optional[Set[str]]]] = []

    def _positive_sources(self, test: ast.expr) -> Optional[Set[str]]:
        """Phases implied by ``test`` being true, from == / in comparisons
        on a job ``.status.phase``; None when the test says nothing."""
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            out: Optional[Set[str]] = None
            for v in test.values:
                got = self._positive_sources(v)
                if got is not None:
                    out = got if out is None else (out & got)
            return out
        if not isinstance(test, ast.Compare) or len(test.ops) != 1:
            return None
        if not _is_job_phase_expr(test.left):
            return None
        op, rhs = test.ops[0], test.comparators[0]
        if isinstance(op, ast.Eq):
            v = _phase_value(rhs, self.attr_to_value, self.const_values)
            return {v} if v is not None else None
        if isinstance(op, ast.In) and isinstance(rhs, (ast.Tuple, ast.List,
                                                       ast.Set)):
            vals = {_phase_value(el, self.attr_to_value, self.const_values)
                    for el in rhs.elts}
            vals.discard(None)
            return set(vals) if vals else None
        return None

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        src = self._positive_sources(node.test)
        self.stack.append(src if src is not None else set())
        pushed = src is not None
        if not pushed:
            self.stack.pop()
        for stmt in node.body:
            self.visit(stmt)
        if pushed:
            self.stack.pop()
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name in TRANSITION_FNS:
            constrained: Optional[Set[str]] = None
            for s in self.stack:
                constrained = set(s) if constrained is None else (
                    constrained & s)
            self.sites.append((node, constrained))
        self.generic_visit(node)


@register_project("TJA013", "phase-transition-exhaustiveness")
def check(pc: ProjectContext) -> List[Finding]:
    const_mod = pc.ensure_module(CONSTANTS_REL)
    if const_mod is None:
        return []
    table = _load_table(const_mod)
    if not table:
        return []
    attr_to_value = _phase_names(pc)
    all_targets: Set[str] = set()
    for targets in table.values():
        all_targets |= targets

    findings: List[Finding] = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or not rel.startswith("trainingjob_operator_tpu/"):
            continue
        if not any(fn in ctx.source for fn in TRANSITION_FNS):
            continue   # cheap text gate before the structured If-stack walk
        walker = _SourceSets(attr_to_value, dict(const_mod.constants))
        walker.visit(ctx.tree)
        for call, sources in walker.sites:
            target_expr = None
            for kw in call.keywords:
                if kw.arg == "ctype":
                    target_expr = kw.value
            if target_expr is None and len(call.args) >= 2:
                target_expr = call.args[1]
            if target_expr is None:
                continue
            target = _phase_value(target_expr, attr_to_value,
                                  {"_": t for t in all_targets | set(table)})
            if target is None and isinstance(target_expr, ast.Attribute) \
                    and isinstance(target_expr.value, ast.Name) \
                    and target_expr.value.id == PHASE_CLASS:
                # TrainingJobPhase attr we couldn't decode (types.py absent
                # from the analyzed tree): skip rather than guess.
                continue
            if target is None:
                continue   # dynamic target (ending_phase variable etc.)
            if target not in all_targets:
                findings.append(Finding(
                    "TJA013", "phase-transition-exhaustiveness", rel,
                    call.lineno, call.col_offset, ERROR,
                    f"phase {target!r} is set here but no PHASE_TRANSITIONS "
                    "entry (api/constants.py) allows any source to reach "
                    "it; declare the transition or fix the target"))
                continue
            for src in sorted(sources or ()):
                if src == target:
                    continue   # same-phase refresh is always legal
                if target not in table.get(src, set()):
                    findings.append(Finding(
                        "TJA013", "phase-transition-exhaustiveness", rel,
                        call.lineno, call.col_offset, ERROR,
                        f"illegal phase transition {src!r} -> {target!r}: "
                        "the dominating phase test witnesses the source, "
                        "but PHASE_TRANSITIONS (api/constants.py) does not "
                        "allow it; fix the code path or extend the table"))
    findings.sort(key=Finding.sort_key)
    return findings
