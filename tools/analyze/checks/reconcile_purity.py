"""TJA003 reconcile-purity: no blocking inside the reconcile plane.

Controller reconcile paths (``controller/*.py``) run on a small fixed pool of
workqueue workers.  One ``time.sleep`` or blocking HTTP/socket call stalls a
worker and, because the workqueue guarantees one-writer-per-key, stalls every
job hashed behind it; an *unbounded* wait can wedge the worker forever.  The
correct idiom is always to return and re-enqueue with
``work_queue.add_after/add_rate_limited`` (SURVEY.md §5.2, Singularity
arxiv 2202.07848 makes the same argument for preemptive schedulers).

Flags, within ``controller/`` modules only:

- ``time.sleep(...)`` (module attribute or from-imported name);
- any call into ``requests``/``urllib``/``socket``/``http``/``subprocess``
  *when that module is imported by the file* (a local variable named
  ``requests`` -- e.g. a k8s resource dict -- is not confused for the module);
- ``.wait()`` / ``.join()`` / ``.acquire()`` / ``.get()`` calls with no
  positional argument and no ``timeout=`` keyword: unbounded.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register

BLOCKING_MODULES = {"requests", "urllib", "socket", "http", "subprocess"}
UNBOUNDED_METHODS = {"wait", "join", "acquire", "get"}


def in_scope(path: str) -> bool:
    return "/controller/" in f"/{path}"


def _root_name(node: ast.expr) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _imported_names(nodes: list) -> Set[str]:
    names: Set[str] = set()
    for node in nodes:
        if isinstance(node, ast.Import):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _sleep_imported_from_time(nodes: list) -> bool:
    for node in nodes:
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            if any((a.asname or a.name) == "sleep" for a in node.names):
                return True
    return False


@register("TJA003", "reconcile-purity")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None or not in_scope(ctx.path):
        return []
    imported = _imported_names(ctx.by_type(ast.Import, ast.ImportFrom))
    bare_sleep = _sleep_imported_from_time(ctx.by_type(ast.ImportFrom))
    findings: List[Finding] = []

    def emit(node: ast.AST, msg: str) -> None:
        findings.append(Finding("TJA003", "reconcile-purity", ctx.path,
                                node.lineno, node.col_offset, ERROR, msg))

    for node in ctx.by_type(ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            root = _root_name(fn.value)
            if fn.attr == "sleep" and root == "time" and "time" in imported:
                emit(node, "time.sleep in a reconcile path blocks a workqueue "
                           "worker; return and re-enqueue with add_after")
                continue
            if root in BLOCKING_MODULES and root in imported:
                emit(node, f"blocking {root}.* call in a reconcile path; "
                           "controllers must not do I/O inline -- re-enqueue "
                           "and let a runtime/background thread block")
                continue
            if (fn.attr in UNBOUNDED_METHODS and not node.args
                    and not any(kw.arg == "timeout" for kw in node.keywords)):
                emit(node, f".{fn.attr}() with no timeout is an unbounded "
                           "wait inside the reconcile plane; pass a timeout "
                           "or restructure via the workqueue")
        elif isinstance(fn, ast.Name) and fn.id == "sleep" and bare_sleep:
            emit(node, "time.sleep in a reconcile path blocks a workqueue "
                       "worker; return and re-enqueue with add_after")
    return findings
