"""TJA007 event-reason-drift: every ``recorder.event()`` reason comes from
the ``EVENT_REASONS`` registry in ``api/constants.py``.

Event reasons are an operational API: dashboards group on them and
``kubectl get events --field-selector reason=...`` filters on them, so an
ad-hoc reason string at one call site is invisible to every consumer keyed
on the registry.  Two failure shapes are flagged:

1. a string literal reason that is not a registry value (either a typo'd
   copy of a registered reason or a brand-new reason that must be declared
   in ``EVENT_REASONS`` first); and
2. a ``constants.X_REASON``-style attribute whose name is a declared
   constant but is *not* listed in the ``EVENT_REASONS`` frozenset (declared
   but unregistered -- the registry is meant to be the closed set).

Only calls whose receiver looks like an event recorder participate
(``recorder`` / ``_recorder`` / ``self.recorder`` / ``rec``): ``.event()``
is too generic a method name to match unconditionally.  Dynamic reasons
(names, f-strings, function calls) are skipped -- this is a drift check,
not a taint analysis.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register

CONSTANTS_REL = "trainingjob_operator_tpu/api/constants.py"
REGISTRY_NAME = "EVENT_REASONS"

#: Receiver leaf names accepted as "an event recorder".
_RECORDER_NAMES = ("recorder", "rec")

_cache: Dict[str, Tuple[float, Set[str], Set[str]]] = {}


def _load_registry(repo_root: str) -> Tuple[Set[str], Set[str]]:
    """(registered constant names, registered string values) from the
    ``EVENT_REASONS`` frozenset in api/constants.py (mtime-cached)."""
    path = os.path.join(repo_root, CONSTANTS_REL)
    # One stat, not an exists + getmtime pair (see constant_drift.py).
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return set(), set()
    cached = _cache.get(path)
    if cached and cached[0] == mtime:
        return cached[1], cached[2]
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    by_name: Dict[str, str] = {}
    member_names: Set[str] = set()
    member_values: Set[str] = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        target = node.targets[0].id
        if (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            by_name[target] = node.value.value
        elif (target == REGISTRY_NAME and isinstance(node.value, ast.Call)
              and isinstance(node.value.func, ast.Name)
              and node.value.func.id == "frozenset" and node.value.args):
            seq = node.value.args[0]
            if isinstance(seq, (ast.Tuple, ast.List, ast.Set)):
                for el in seq.elts:
                    if isinstance(el, ast.Name) and el.id in by_name:
                        member_names.add(el.id)
                        member_values.add(by_name[el.id])
                    elif (isinstance(el, ast.Constant)
                          and isinstance(el.value, str)):
                        member_values.add(el.value)
    _cache[path] = (mtime, member_names, member_values)
    return member_names, member_values


def _repo_root(ctx: FileContext) -> Optional[str]:
    suffix = ctx.path.replace("/", os.sep)
    if ctx.abs_path.endswith(suffix):
        return ctx.abs_path[:-len(suffix)].rstrip(os.sep) or os.sep
    return None


def _leaf_name(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_recorder_call(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Attribute) and call.func.attr == "event"):
        return False
    leaf = _leaf_name(call.func.value).lower().lstrip("_")
    return any(leaf == n or leaf.endswith(n) for n in _RECORDER_NAMES)


def _reason_arg(call: ast.Call) -> Optional[ast.expr]:
    # EventRecorder.event(obj, etype, reason, message): positional index 2.
    for kw in call.keywords:
        if kw.arg == "reason":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


@register("TJA007", "event-reason-drift")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None or ".event(" not in ctx.source:
        return []
    root = _repo_root(ctx)
    if root is None:
        return []
    member_names, member_values = _load_registry(root)
    if not member_values:
        return []
    findings: List[Finding] = []
    for node in ctx.by_type(ast.Call):
        if not _is_recorder_call(node):
            continue
        reason = _reason_arg(node)
        if reason is None:
            continue
        if isinstance(reason, ast.Constant) and isinstance(reason.value, str):
            if reason.value not in member_values:
                findings.append(Finding(
                    "TJA007", "event-reason-drift", ctx.path, reason.lineno,
                    reason.col_offset, ERROR,
                    f"event reason {reason.value!r} is not in the "
                    "EVENT_REASONS registry (api/constants.py); declare it "
                    "there and pass the constant (ad-hoc reasons are "
                    "invisible to reason-keyed dashboards and filters)"))
        elif isinstance(reason, ast.Attribute):
            name = reason.attr
            if (name.isupper() and name not in member_names
                    and name.endswith("_REASON")):
                findings.append(Finding(
                    "TJA007", "event-reason-drift", ctx.path, reason.lineno,
                    reason.col_offset, ERROR,
                    f"event reason constant {name} is not listed in "
                    "EVENT_REASONS (api/constants.py); add it to the "
                    "registry frozenset so the closed set stays closed"))
    return findings
