"""TJA016 lock-held-blocking-call: I/O reachable while a lock is held.

TJA010 proves lock *order*; this pass proves lock *latency*: a blocking
callee -- socket ops, ``time.sleep``, unbounded ``join``/``wait``/``get``,
HTTP, subprocess -- reachable while a lock is must-held.  One slow peer then
stalls every thread contending for that lock: the pserver's ``handle``
threads serializing ``send_msg`` under the shard lock block *all* workers
behind one worker's congested socket.

Three witnesses, in decreasing precision:

1. **Summary-held calls** (PR 4's ``held_calls``): a method calls, under
   ``with self._lock:``, a project callable that may block *transitively*
   (fixpoint over the call graph, same resolver as TJA010).
2. **Lexical with-bodies everywhere**, including nested/closure functions
   the summaries deliberately skip: direct blocking calls (name-level
   classifier in _flow.py) or may-blocking project callees inside
   ``with <lock>:`` where the lock is a ``self.*`` lock attr, a module
   lock, or a function-local/closure ``threading.Lock()``.
3. **Path-sensitive manual locking**: ``l.acquire() ... l.release()`` pairs
   tracked by a forward *must* analysis over the CFG -- a blocking call is
   flagged only when the lock is held on *every* path reaching it, and the
   engine's exception rule means a release in a ``finally`` is honored on
   exceptional paths too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze import dataflow
from tools.analyze.findings import (ERROR, Finding, walk_fast,
                                    _LOCAL_BARRIERS)
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project
from tools.analyze.checks._flow import (
    blocking_reason, enclosing, functions_of, parents_of, walk_local,
)
from tools.analyze.checks.lock_order import _Resolver, _iter_summaries
from tools.analyze.project import LOCK_FACTORIES


class _FnFacts:
    """One sweep per file, shared by every stage of this pass."""

    __slots__ = ("locks", "withs", "has_acquire", "blocking")

    def __init__(self):
        self.locks: Set[str] = set()
        self.withs: List[ast.AST] = []
        self.has_acquire = False
        self.blocking: List[Tuple[ast.Call, str]] = []


def _collect_facts(ctx, fns) -> Dict[int, "_FnFacts"]:
    """Facts for every function of one file, from a single sweep of the
    relevant by_type buckets with each node attributed to its owning
    function by parent-chain (#interesting-nodes x depth) -- re-walking
    every function body (#all-nodes) was this pass's hottest profile line.
    Owner == nearest scope barrier reproduces walk_local membership: nodes
    inside a nested lambda/class belong to it, not to the enclosing def."""
    facts = {id(fn): _FnFacts() for fn in fns}
    parents = ctx.parents
    barriers = _LOCAL_BARRIERS
    for node in ctx.by_type(ast.Call, ast.With, ast.AsyncWith, ast.Assign):
        cur = parents.get(id(node))
        while cur is not None and cur.__class__ not in barriers:
            cur = parents.get(id(cur))
        if cur is None:
            continue
        ff = facts.get(id(cur))
        if ff is None:
            continue
        ncls = node.__class__
        if ncls is ast.Call:
            if node.func.__class__ is ast.Attribute \
                    and node.func.attr == "acquire":
                ff.has_acquire = True
            why = blocking_reason(node)
            if why is not None:
                ff.blocking.append((node, why))
        elif ncls is ast.With or ncls is ast.AsyncWith:
            ff.withs.append(node)
        elif node.value.__class__ is ast.Call:
            f = node.value.func
            name = f.id if f.__class__ is ast.Name else (
                f.attr if f.__class__ is ast.Attribute else None)
            if name in LOCK_FACTORIES:
                ff.locks |= {t.id for t in node.targets
                             if t.__class__ is ast.Name}
    return facts


def _may_block(pc: ProjectContext, res: _Resolver,
               facts_of: Dict[int, _FnFacts]) -> Dict[str, str]:
    """summary qual -> blocking reason, closed transitively over the call
    graph (the TJA010 fixpoint shape, with reasons instead of lock sets)."""
    reason: Dict[str, str] = {}
    callees: Dict[str, Set[str]] = {}
    for mod, cls, s in _iter_summaries(pc):
        ff = facts_of.get(id(s.node))
        if ff is not None and ff.blocking:
            reason[s.qual] = ff.blocking[0][1]
        outs: Set[str] = set()
        for call in {c[:-1] for c in s.calls}:
            for _m, _c, cs in res.callee_summaries(mod, cls, call):
                outs.add(cs.qual)
        callees[s.qual] = outs
    changed = True
    while changed:
        changed = False
        for q, outs in callees.items():
            if q in reason:
                continue
            for o in outs:
                if o in reason:
                    reason[q] = f"{o.rsplit('.', 1)[-1]}() -> {reason[o]}"
                    changed = True
                    break
    return reason


def _lock_name_of(expr: ast.expr, self_locks: Set[str], module_locks: Set[str],
                  scope_locks: Set[str]) -> Optional[str]:
    """Printable lock name when a ``with`` item is a known lock."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and expr.attr in self_locks:
        return f"self.{expr.attr}"
    if isinstance(expr, ast.Name) and (expr.id in module_locks
                                       or expr.id in scope_locks):
        return expr.id
    return None


class _Held(dataflow.Analysis):
    """Must-held lock names through manual acquire()/release() pairs."""

    may = False

    def __init__(self, lockish: Set[str]):
        self.lockish = lockish

    def _lock_call(self, stmt: ast.AST, attr: str) -> Optional[str]:
        for node in walk_fast(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == attr:
                recv = node.func.value
                if isinstance(recv, ast.Name) and recv.id in self.lockish:
                    return recv.id
                if isinstance(recv, ast.Attribute) \
                        and isinstance(recv.value, ast.Name) \
                        and recv.value.id == "self" \
                        and recv.attr in self.lockish:
                    return f"self.{recv.attr}"
        return None

    def gen(self, stmt: ast.AST):
        got = self._lock_call(stmt, "acquire")
        return [got] if got else []

    def kill(self, stmt: ast.AST, facts):
        got = self._lock_call(stmt, "release")
        return [got] if got else []


@register_project("TJA016", "lock-held-blocking-call")
def check(pc: ProjectContext) -> List[Finding]:
    res = _Resolver(pc)
    facts_of: Dict[int, _FnFacts] = {}
    fns_by_file: Dict[str, list] = {}
    for rel, ctx in pc.files.items():
        if ctx.tree is None:
            continue
        fns = functions_of(ctx)
        fns_by_file[rel] = fns
        facts_of.update(_collect_facts(ctx, fns))
    may_block = _may_block(pc, res, facts_of)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()

    def report(path: str, line: int, lock: str, why: str) -> None:
        if (path, line) in seen:
            return
        seen.add((path, line))
        findings.append(Finding(
            "TJA016", "lock-held-blocking-call", path, line, 0, ERROR,
            f"blocking call ({why}) while holding lock {lock}; move the "
            f"I/O out of the locked region or bound it with a timeout"))

    # 1. Transitive blocking through summary-held calls (with self.X:).
    for mod, cls, s in _iter_summaries(pc):
        for lock, callee, line in s.held_calls:
            hit = res.lock_id(mod, cls, lock)
            if hit is None:
                continue
            for _m, _c, cs in res.callee_summaries(mod, cls, callee):
                why = may_block.get(cs.qual)
                if why is not None:
                    report(mod.ctx.path, line,
                           hit[0].rsplit(".", 2)[-1], why)

    # 2. Lexical with-lock bodies in every function, nested ones included.
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None:
            continue
        mod = pc.module_of_path(rel)
        module_locks = set(mod.module_locks) if mod else set()
        parents = parents_of(ctx)
        for fn in fns_by_file.get(rel, ()):
            ff = facts_of[id(fn)]
            if not (ff.withs or ff.has_acquire):
                continue
            cls_node = enclosing(parents, fn, ast.ClassDef)
            cls = None
            self_locks: Set[str] = set()
            if mod is not None and cls_node is not None \
                    and cls_node.name in mod.classes:
                cls = mod.classes[cls_node.name]
                for k in pc.mro_classes(cls):
                    self_locks |= set(k.lock_attrs)
            scope_locks = set(ff.locks)
            anc = enclosing(parents, fn, ast.FunctionDef,
                            ast.AsyncFunctionDef)
            while anc is not None:
                aff = facts_of.get(id(anc))
                if aff is not None:
                    scope_locks |= aff.locks
                anc = enclosing(parents, anc, ast.FunctionDef,
                                ast.AsyncFunctionDef)
            for w in ff.withs:
                locks = [_lock_name_of(i.context_expr, self_locks,
                                       module_locks, scope_locks)
                         for i in w.items]
                locks = [l for l in locks if l]
                if not locks:
                    continue
                for node in walk_local(w):
                    if not isinstance(node, ast.Call):
                        continue
                    why = blocking_reason(node)
                    if why is None and mod is not None:
                        callee = _callee_tuple(node)
                        if callee is not None:
                            for _m, _c, cs in res.callee_summaries(
                                    mod, cls, callee):
                                why = may_block.get(cs.qual)
                                if why is not None:
                                    why = (f"{callee[-1]}() -> {why}"
                                           if "->" not in why else why)
                                    break
                    if why is not None and not _is_lock_op(node, locks):
                        report(rel, node.lineno, locks[0], why)

            # 3. Manual acquire/release pairs, path-sensitively.
            lockish = ({a for a in self_locks} | module_locks | scope_locks)
            if not ff.has_acquire:
                continue
            cfg = ctx.cfg(fn)
            sol = dataflow.solve(cfg, _Held(lockish))
            for block in cfg.blocks:
                for stmt, before, _after in sol.walk(block):
                    if not before:
                        continue
                    for node in walk_fast(stmt):
                        if isinstance(node, ast.Call):
                            why = blocking_reason(node)
                            if why is not None \
                                    and not _is_lock_op(node, before):
                                report(rel, node.lineno,
                                       sorted(before)[0], why)

    findings.sort(key=Finding.sort_key)
    return findings


def _callee_tuple(call: ast.Call) -> Optional[tuple]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return ("name", fn.id)
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            return ("self", fn.attr)
        if isinstance(recv, ast.Name):
            return ("attr", recv.id, fn.attr)
        if isinstance(recv, ast.Attribute) and isinstance(recv.value,
                                                          ast.Name) \
                and recv.value.id == "self":
            return ("attr", recv.attr, fn.attr)
    return None


def _is_lock_op(call: ast.Call, held) -> bool:
    """The acquire()/release() on the held lock itself is not 'blocking
    I/O under the lock' -- it IS the lock."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute)
            and fn.attr in ("acquire", "release")):
        return False
    recv = fn.value
    name = None
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name) \
            and recv.value.id == "self":
        name = f"self.{recv.attr}"
    return name is not None and any(name == h or h.endswith(name)
                                    for h in held)


