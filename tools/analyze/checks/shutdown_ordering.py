"""TJA031 shutdown-ordering: retained threads are joined, and not under
a lock the thread itself takes.

A class that stores its spawned thread (``self._thread = Thread(...)``
or ``self._workers.append(th)``) and exposes a stop path
(``stop``/``shutdown``/``shut_down``/``close``/``request_stop``) has
made a lifecycle promise: shutdown reclaims the thread.  Two ways that
promise silently breaks:

- **Never joined.**  No stop path joins the retained handle, so the
  thread outlives shutdown and races teardown -- flushing to a closed
  sink, reconciling a deleted store, segfault-adjacent behaviour that
  only shows under load.  WARNING at the spawn site (daemon threads are
  still flagged: daemonhood changes process exit, not teardown races).

- **Joined under the wrong lock.**  A stop path that joins while
  holding a lock the role's closure also acquires deadlocks the first
  time the thread happens to be blocked on that lock at shutdown --
  stop() waits on the thread, the thread waits on stop()'s lock.
  ERROR at the join site, naming the shared lock.

Role/closure/lock facts all come from the thread-model layer; roles
whose handle is never retained have no join obligation (the spawner
provably cannot join them -- that is a design choice, not drift).
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from tools.analyze import threadmodel
from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.project import MethodSummary, ProjectContext, _self_attr
from tools.analyze.runner import register_project

CHECK_ID, CHECK_NAME = "TJA031", "shutdown-ordering"


def _is_join(n: ast.AST) -> bool:
    return isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
        and n.func.attr == "join"


def _join_sites(s: MethodSummary, attr, list_attr) -> List[int]:
    """Lines in a stop summary that join the retained handle: a direct
    ``self.<attr>.join(...)``, a join through a local alias
    (``th = self._thread; th.join(...)``), or any loop-variable
    ``.join(...)`` inside a ``for ... in self.<list_attr>:`` loop."""
    aliases = set()
    if attr is not None:
        for n in ast.walk(s.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and _self_attr(n.value) == attr:
                aliases.add(n.targets[0].id)
    out: List[int] = []
    for n in ast.walk(s.node):
        if isinstance(n, ast.For) and list_attr is not None \
                and _self_attr(n.iter) == list_attr:
            for m in ast.walk(n):
                if _is_join(m) and isinstance(m.func.value, ast.Name):
                    out.append(m.lineno)
        elif _is_join(n):
            recv = n.func.value
            if (attr is not None and _self_attr(recv) == attr) \
                    or (isinstance(recv, ast.Name) and recv.id in aliases):
                out.append(n.lineno)
    return out


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    tm = threadmodel.model(pc)
    findings: List[Finding] = []
    for name in sorted(tm.roles):
        role = tm.roles[name]
        if role.kind != "thread" or role.owner_class is None:
            continue
        attr = role.thread_attr or role.thread_list_attr
        if attr is None:
            continue   # handle never retained: no join obligation
        stops: List[Tuple[str, MethodSummary]] = \
            tm.stop_summaries(role.owner_class)
        if not stops:
            continue
        joined = False
        for path, s in stops:
            for line in _join_sites(s, role.thread_attr,
                                    role.thread_list_attr):
                joined = True
                held = tm.lock_set(path, line) & tm.role_lock_ids(name)
                if held:
                    findings.append(Finding(
                        CHECK_ID, CHECK_NAME, path, line, 0, ERROR,
                        f"{s.qual} joins thread role {name} while holding "
                        f"{', '.join(sorted(held))}, which the role's "
                        "closure also acquires: if the thread is blocked "
                        "on that lock at shutdown, stop() waits on the "
                        "thread and the thread waits on stop() -- join "
                        "outside the locked region"))
        if not joined:
            stop_names = ", ".join(sorted(s.qual for _p, s in stops))
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, role.spawn_path, role.spawn_line, 0,
                WARNING,
                f"thread role {name} is retained as self.{attr} but no "
                f"stop path ({stop_names}) joins it; the thread outlives "
                "shutdown and races teardown -- join it (with a timeout) "
                "from the stop path"))
    findings.sort(key=Finding.sort_key)
    return findings
