"""TJA011 env-contract: three-way consistency for the rendezvous env.

The controller's entire interface with workloads is environment variables
(PAPER.md's env-injection design): ``controller/pod.py`` bakes
``TRAININGJOB_*`` / ``TPU_WORKER_*`` / ``MEGASCALE_*`` vars into pod specs,
runtimes forward them into processes, and ``workloads/``/``runtime/`` read
them back.  Because the two halves never share code -- only strings -- the
contract can drift silently in three directions, and this pass closes the
triangle project-wide:

1. **read-but-never-injected** (error): code reads a contract var that no
   injection site sets and that is not declared a user knob
   (``USER_ENV_KNOBS`` in api/constants.py) -- the read can only ever see
   its default, which usually means a rename landed on one side only;
2. **injected-but-never-read** (warning): the controller injects a declared
   var that nothing in the project reads and that is not declared
   externally consumed (``EXTERNAL_CONSUMER_ENV``) -- dead contract
   surface that every future reader must reverse-engineer;
3. **undeclared** (error): a contract-shaped var is read or injected via a
   raw literal that ``api/constants.py`` does not define (TJA005 flags this
   per-file in controller/runtime/workloads; this pass covers the whole
   package, including ``ops/`` and ``data/``).

Evidence is syntactic: injection is ``EnvVar(X, ...)``, ``env[X] = ...`` or
``env.setdefault(X, ...)``; a read is ``X`` appearing as the key argument
of a ``.get``/``getenv``/``.pop`` call, as a ``Load`` subscript index, as a
parameter default, or as the first argument to an ``_env``-named helper.
``X`` may be a ``constants.*`` attribute or a string literal.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.project import ModuleInfo, ProjectContext
from tools.analyze.runner import register_project

CONSTANTS_REL = "trainingjob_operator_tpu/api/constants.py"
CONTRACT_ENV_RE = re.compile(
    r"^(TRAININGJOB_[A-Z0-9_]+|TPU_WORKER_[A-Z0-9_]+|MEGASCALE_[A-Z0-9_]+)$")

#: Call-leaf names whose string key argument is a read.
_READ_CALLS = {"get", "getenv", "pop"}
#: Receiver/callee substrings marking an env helper (``_env_float(X, d)``).
_ENV_HELPER_RE = re.compile(r"(^|_)env", re.IGNORECASE)


def _frozenset_values(mod: ModuleInfo, name: str) -> Set[str]:
    """String values of a ``NAME = frozenset((A, B, ...))`` declaration,
    resolving member names through the module's own string constants."""
    out: Set[str] = set()
    if mod.ctx is None or mod.ctx.tree is None:
        return out
    for node in mod.ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "frozenset" and node.value.args):
            continue
        seq = node.value.args[0]
        if isinstance(seq, (ast.Tuple, ast.List, ast.Set)):
            for el in seq.elts:
                if isinstance(el, ast.Name) and el.id in mod.constants:
                    out.add(mod.constants[el.id])
                elif isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
    return out


def _env_value(node: ast.expr, constants: Dict[str, str],
               local_consts: Dict[str, str]) -> Optional[str]:
    """The env-var name an expression denotes: a string literal, a
    ``constants.X`` attribute, or a module-local ``X`` constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Attribute) and node.attr in constants:
        return constants[node.attr]
    if isinstance(node, ast.Name) and node.id in local_consts:
        return local_consts[node.id]
    return None


class _Collector:
    """Evidence collection over one file's typed node buckets.  Every rule
    here is context-free (a node alone decides), so there is no need for a
    recursive NodeVisitor walk -- iterating the by_type buckets covers all
    nested occurrences at a fraction of the traversal cost."""

    def __init__(self, path: str, constants: Dict[str, str],
                 local_consts: Dict[str, str]):
        self.path = path
        self.constants = constants
        self.local_consts = local_consts
        #: value -> first (path, line) evidence.
        self.injected: Dict[str, Tuple[str, int]] = {}
        self.read: Dict[str, Tuple[str, int]] = {}
        self.undeclared: List[Tuple[str, int, str]] = []   # (value, line, how)

    def _note(self, store: Dict[str, Tuple[str, int]], value: str,
              line: int, how: str) -> None:
        store.setdefault(value, (self.path, line))
        if (CONTRACT_ENV_RE.match(value)
                and value not in self.constants.values()):
            self.undeclared.append((value, line, how))

    def _key(self, node: ast.expr) -> Optional[str]:
        return _env_value(node, self.constants, self.local_consts)

    def collect(self, ctx) -> None:
        for node in ctx.by_type(ast.Call):
            self._call(node)
        for node in ctx.by_type(ast.Subscript):
            self._subscript(node)
        for node in ctx.by_type(ast.arguments):
            self._defaults(node)
        for node in ctx.by_type(ast.Compare):
            self._compare(node)

    def _call(self, node: ast.Call) -> None:
        fn = node.func
        leaf = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if leaf == "EnvVar" and node.args:
            v = self._key(node.args[0])
            if v is not None:
                self._note(self.injected, v, node.lineno, "injected")
        elif leaf == "setdefault" and node.args:
            v = self._key(node.args[0])
            if v is not None and CONTRACT_ENV_RE.match(v):
                self._note(self.injected, v, node.lineno, "injected")
        elif leaf in _READ_CALLS and node.args:
            v = self._key(node.args[0])
            if v is not None:
                self._note(self.read, v, node.lineno, "read")
        elif _ENV_HELPER_RE.search(leaf) and node.args:
            v = self._key(node.args[0])
            if v is not None and CONTRACT_ENV_RE.match(v):
                self._note(self.read, v, node.lineno, "read")

    def _subscript(self, node: ast.Subscript) -> None:
        v = self._key(node.slice)
        if v is not None and CONTRACT_ENV_RE.match(v):
            if isinstance(node.ctx, ast.Store):
                self._note(self.injected, v, node.lineno, "injected")
            else:
                self._note(self.read, v, node.lineno, "read")

    def _defaults(self, node: ast.arguments) -> None:
        for default in list(node.defaults) + [d for d in node.kw_defaults if d]:
            v = self._key(default)
            if v is not None and CONTRACT_ENV_RE.match(v):
                # ``def from_env(var=constants.X_ENV)``: the function reads
                # os.environ[var] dynamically -- count the default as a read.
                self._note(self.read, v, default.lineno, "read")

    def _compare(self, node: ast.Compare) -> None:
        # ``constants.X_ENV in os.environ`` -- membership probe is a read.
        if any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            v = self._key(node.left)
            if v is not None and CONTRACT_ENV_RE.match(v):
                self._note(self.read, v, node.lineno, "read")


@register_project("TJA011", "env-contract")
def check(pc: ProjectContext) -> List[Finding]:
    const_mod = pc.ensure_module(CONSTANTS_REL)
    if const_mod is None:
        return []
    constants = {n: v for n, v in const_mod.constants.items()
                 if n.endswith("_ENV")}
    declared = set(constants.values())
    user_knobs = _frozenset_values(const_mod, "USER_ENV_KNOBS")
    external = _frozenset_values(const_mod, "EXTERNAL_CONSUMER_ENV")
    decl_lines = {}
    if const_mod.ctx is not None and const_mod.ctx.tree is not None:
        for node in const_mod.ctx.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                decl_lines[node.value.value] = node.lineno

    injected: Dict[str, Tuple[str, int]] = {}
    read: Dict[str, Tuple[str, int]] = {}
    findings: List[Finding] = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or rel == CONSTANTS_REL \
                or not rel.startswith("trainingjob_operator_tpu/"):
            continue
        mod = pc.module_of_path(rel)
        local_consts = dict(mod.constants) if mod is not None else {}
        col = _Collector(rel, constants, local_consts)
        col.collect(ctx)
        for v, site in col.injected.items():
            injected.setdefault(v, site)
        for v, site in col.read.items():
            read.setdefault(v, site)
        for v, line, how in col.undeclared:
            findings.append(Finding(
                "TJA011", "env-contract", rel, line, 0, ERROR,
                f"contract env var {v!r} is {how} here but not declared in "
                "api/constants.py; declare it (and add it to USER_ENV_KNOBS "
                "if the controller never injects it)"))

    # The two absence-based directions are whole-package claims: skip them
    # unless the analyzed set actually covers the package.
    if not pc.covers_package("trainingjob_operator_tpu"):
        findings.sort(key=Finding.sort_key)
        return findings

    for v in sorted(read):
        if not CONTRACT_ENV_RE.match(v):
            continue
        if v in injected or v in user_knobs or v not in declared:
            continue   # undeclared reads already reported above
        path, line = read[v]
        findings.append(Finding(
            "TJA011", "env-contract", path, line, 0, ERROR,
            f"env var {v!r} is read here but never injected by the "
            "controller or a runtime, and is not in USER_ENV_KNOBS "
            "(api/constants.py): the read can only see its default"))

    for v in sorted(injected):
        if not CONTRACT_ENV_RE.match(v):
            continue
        if v in read or v in external or v not in declared:
            continue
        path, line = injected[v]
        findings.append(Finding(
            "TJA011", "env-contract", path, line, 0, WARNING,
            f"env var {v!r} is injected here but nothing in the project "
            "reads it and it is not in EXTERNAL_CONSUMER_ENV "
            "(api/constants.py): dead contract surface"))

    findings.sort(key=Finding.sort_key)
    return findings
