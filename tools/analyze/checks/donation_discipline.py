"""TJA022 donation-discipline: donated buffers and the ones that should be.

``donate_argnums`` lets XLA alias an input buffer to an output, so a
state-in/state-out step (``params, opt = step(params, opt, batch)``; the
serve K/V cache) runs without holding two copies of the state in HBM --
the difference between fitting and OOM at the sizes the paper's jobs run
(PAPER.md; the snapshot-donate checkpoint path was built on exactly this).
Donation has a sharp edge though: the donated input buffer is *gone* after
the call, and reading it afterwards returns garbage or raises.

Two rules over the ``jit_boundary`` layer:

- **read-after-donate** (error): an argument at a donated position, when
  it is a plain name or ``self.attr``, must be rebound by the call's own
  assignment targets or not read again afterwards; a donating call inside
  a loop that does not rebind feeds the dead buffer back next iteration.
  (Line-order approximation; a rebind between the call and the read
  kills the finding.)
- **missing-donation** (advisory): a hot-path call into a jitted binding
  that round-trips the same names in and out, where the binding's wrap
  site has no ``donate_argnums``/``donate_argnames`` at all.  Advisory:
  donation is wrong when the caller keeps the old state on purpose
  (the elastic reshard keeps pre-resize state alive until the exchange
  commits), so fixing vs waiving is a per-site decision.

``tests/`` are exempt.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from tools.analyze import jit_boundary as jb
from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project


def _is_test_path(path: str) -> bool:
    return path.startswith("tests/") or "/tests/" in path


def _as_ref(arg: ast.expr):
    """A trackable donated operand: 'name' or ('self', attr)."""
    if isinstance(arg, ast.Name):
        return arg.id
    if (isinstance(arg, ast.Attribute)
            and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"):
        return ("self", arg.attr)
    return None


def _name_of(ref) -> str:
    return ref if isinstance(ref, str) else f"self.{ref[1]}"


def _next_event(rec: jb.FnRec, ref, after_line: int) -> Optional[Tuple[str, int]]:
    """First ('load'|'store', line) for ``ref`` strictly after a line."""
    best: Optional[Tuple[int, str]] = None
    for n in ast.walk(rec.node):
        line = getattr(n, "lineno", None)
        if line is None or line <= after_line:
            continue
        kind = None
        if isinstance(ref, str) and isinstance(n, ast.Name) and n.id == ref:
            kind = "store" if isinstance(n.ctx, ast.Store) else "load"
        elif (not isinstance(ref, str) and isinstance(n, ast.Attribute)
                and n.attr == ref[1] and isinstance(n.value, ast.Name)
                and n.value.id == "self"):
            kind = "store" if isinstance(n.ctx, ast.Store) else "load"
        if kind and (best is None or line < best[0]):
            best = (line, kind)
    if best is None:
        return None
    return best[1], best[0]


@register_project("TJA022", "donation-discipline")
def check(pc: ProjectContext) -> List[Finding]:
    b = jb.boundary(pc)
    findings: List[Finding] = []

    def emit(path: str, line: int, col: int, sev: str, msg: str) -> None:
        findings.append(Finding("TJA022", "donation-discipline", path,
                                line, col, sev, msg))

    # -- read-after-donate (all scopes) ---------------------------------------
    for qual, rec in b.fns.items():
        if _is_test_path(rec.path):
            continue
        for cr in rec.calls:
            site = b.site_for_call(rec, cr)
            if site is None or not (site.donate_argnums
                                    or site.donate_argnames):
                continue
            call = cr.node
            donated = []
            for idx in site.donate_argnums:
                if idx < len(call.args):
                    donated.append(call.args[idx])
            for kw in call.keywords:
                if kw.arg and kw.arg in site.donate_argnames:
                    donated.append(kw.value)
            for arg in donated:
                ref = _as_ref(arg)
                if ref is None:
                    continue
                if ref in cr.targets:
                    continue        # x = f(x): rebound, the normal shape
                nm = _name_of(ref)
                if cr.loop_stack:
                    emit(rec.path, call.lineno, call.col_offset, ERROR,
                         f"'{nm}' is donated to the {site.describe()} "
                         "inside a loop without being rebound by the "
                         "call's result; next iteration passes a dead "
                         "buffer")
                    continue
                after = getattr(call, "end_lineno", call.lineno)
                ev = _next_event(rec, ref, after)
                if ev is not None and ev[0] == "load":
                    emit(rec.path, call.lineno, call.col_offset, ERROR,
                         f"'{nm}' is donated to the {site.describe()} but "
                         f"read again at line {ev[1]}; the donated buffer "
                         "is dead after the call -- rebind the result or "
                         "drop the donation")

    # -- missing-donation advisory (hot path only) ----------------------------
    advised: Set[int] = set()
    hot_scopes = [(hl.fn_qual, hl, True) for hl in b.hot_loops]
    hot_scopes += [(q, hl, False) for q, hl in b.hot_fns.items()]
    for qual, hl, loop_only in hot_scopes:
        rec = b.fns.get(qual)
        if rec is None or _is_test_path(rec.path):
            continue
        loops = [lp for lp in rec.loops if lp.lineno == hl.line] \
            if loop_only else []
        for cr in rec.calls:
            if loop_only and not any(lp in cr.loop_stack for lp in loops):
                continue
            site = b.site_for_call(rec, cr)
            if site is None or site.has_donate or id(site) in advised:
                continue
            refs = set()
            for a in cr.node.args:
                r = _as_ref(a)
                if r is not None:
                    refs.add(r)
            carried = sorted(_name_of(r) for r in refs
                             if r in set(cr.targets))
            if not carried:
                continue
            advised.add(id(site))
            emit(rec.path, cr.node.lineno, cr.node.col_offset, WARNING,
                 f"state-in/state-out step on the hot path round-trips "
                 f"{carried} through the {site.describe()}, which has no "
                 "donate_argnums; donating the state input lets XLA alias "
                 "it to the output and halves its peak HBM (waive if the "
                 "old state must stay readable)")

    findings.sort(key=Finding.sort_key)
    return findings
