"""TJA018 retry-without-backoff: hot retry loops against remote peers.

``while True: try: client.call() except Exception: continue`` is how one
flapping apiserver turns into a tight loop of failing RPCs -- each iteration
fails in microseconds, so the loop burns a core and hammers the exact
endpoint that is trying to recover.  Every client-facing retry loop must
pause on its back edge (sleep, bounded wait, rate limiter).

The CFG makes "on its back edge" precise.  A finding requires all of:

- a ``while`` loop (``for`` loops iterate *independent* items -- skipping a
  bad record is not a retry);
- a ``try`` in the loop body whose handler *swallows* (no ``raise``, no
  ``return``, no ``break``: control re-enters the loop);
- the handler catches something other than a timeout type (``socket.timeout``
  / ``TimeoutError`` / queue ``Empty``/``Full`` -- there the blocking wait
  itself already paced the loop);
- the ``try`` body performs an I/O-ish call (sockets, HTTP, or a
  client/conn/api-shaped receiver);
- and, on the CFG, a normal-control path from the handler entry back to the
  try entry that passes **no backoff call** (``_flow.is_backoff_call``) --
  pacing at the loop top or in the handler both break the path, anywhere
  else does not help.

A second, advisory form (``retry-backoff-no-jitter``) fires when the loop
IS paced but every pacing call in it is a constant-literal ``sleep`` --
scoped to ``client/`` and ``controller/`` code, where N replicas retrying
against one recovering apiserver with the same fixed period re-arrive in
lockstep (thundering herd).  A computed delay (exponential ladder, jittered
policy, ``backoff``-named helper) is assumed to decorrelate and stays
quiet; client/retry.py is the blessed implementation.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from tools.analyze.findings import Finding, WARNING
from tools.analyze.findings import FileContext, walk_fast
from tools.analyze.runner import register
from tools.analyze.checks._flow import (
    call_dotted, enclosing, functions_of, is_backoff_call, parents_of,
    walk_local,
)
from tools.analyze.cfg import handler_type_names

#: Handler types where the failed call was itself the pause.
TIMEOUT_TYPES = {"timeout", "TimeoutError", "Empty", "Full"}

#: Receiver names (underscores stripped) that mark a remote-API call.
CLIENT_RECEIVERS = {"client", "api", "conn", "sock", "socket", "session",
                    "server", "stub", "http", "channel"}

#: Attribute callees that are remote I/O wherever they appear.
IO_ATTRS = {"request", "urlopen", "sendall", "recv", "recvfrom", "connect",
            "accept", "getresponse", "watch"}

IO_NAMES = {"send_msg", "recv_msg", "create_connection", "urlopen"}


def _receiver_leaf(call: ast.Call) -> Optional[str]:
    node = call.func
    if not isinstance(node, ast.Attribute):
        return None
    node = node.value
    while isinstance(node, ast.Attribute):
        # self._client.list -> "_client"; keep the attribute leaf.
        return node.attr.strip("_").lower()
    if isinstance(node, ast.Name):
        return node.id.strip("_").lower()
    return None


def _is_api_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id in IO_NAMES
    if isinstance(fn, ast.Attribute):
        if fn.attr in IO_ATTRS:
            return True
        recv = _receiver_leaf(call)
        if recv in CLIENT_RECEIVERS:
            return True
        dotted = call_dotted(call) or ""
        root = dotted.split(".", 1)[0]
        return root in ("socket", "urllib", "http")
    return False


def _herd_scoped(path: str) -> bool:
    """Only control-plane client/controller code retries against the one
    shared apiserver at fleet multiplicity; elsewhere a fixed sleep has no
    herd to synchronize."""
    parts = path.replace(os.sep, "/").split("/")
    return "client" in parts or "controller" in parts


def _constant_sleep(call: ast.Call) -> bool:
    """``time.sleep(0.5)``-shaped: a sleep whose every argument is a bare
    literal, so all retriers share one fixed period.  Computed delays and
    ``backoff``-named helpers do not count."""
    dotted = call_dotted(call) or ""
    if dotted.rsplit(".", 1)[-1] != "sleep":
        return False
    return bool(call.args) and all(
        isinstance(a, ast.Constant) for a in call.args) and not call.keywords


def _swallows(handler: ast.ExceptHandler) -> bool:
    for node in walk_fast(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return False
    return True


def _handler_is_timeout_only(handler: ast.ExceptHandler) -> bool:
    names = handler_type_names(handler)
    return bool(names) and all(n in TIMEOUT_TYPES for n in names)


@register("TJA018", "retry-without-backoff")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    findings: List[Finding] = []
    parents = parents_of(ctx)
    for fn in functions_of(ctx):
        tries = [n for n in walk_local(fn) if isinstance(n, ast.Try)]
        if not tries:
            continue
        cfg = None
        advised: Set[int] = set()  # loops already carrying the jitter advisory
        for t in tries:
            loop = enclosing(parents, t, ast.While, ast.For, ast.AsyncFor,
                             ast.FunctionDef, ast.AsyncFunctionDef)
            if not isinstance(loop, ast.While):
                continue
            if not any(isinstance(n, ast.Call) and _is_api_call(n)
                       for b in t.body for n in walk_fast(b)):
                continue
            for handler in t.handlers:
                if not _swallows(handler) or _handler_is_timeout_only(handler):
                    continue
                if cfg is None:
                    cfg = ctx.cfg(fn)
                h_entry = cfg.block_of.get(id(handler))
                t_entry = cfg.block_of.get(id(t.body[0]))
                if h_entry is None or t_entry is None:
                    continue
                paced = {b.bid for b in cfg.blocks
                         if any(isinstance(n, ast.Call) and is_backoff_call(n)
                                for s in b.stmts
                                for n in walk_fast(s))}
                if cfg.reaches(h_entry, t_entry, blocked=paced):
                    caught = ", ".join(handler_type_names(handler))
                    findings.append(Finding(
                        "TJA018", "retry-without-backoff", ctx.path,
                        handler.lineno, 0, WARNING,
                        f"retry loop in {fn.name}() re-enters the I/O call "
                        f"after catching {caught} with no sleep/backoff on "
                        f"the back edge; add time.sleep or a rate limiter "
                        f"before retrying"))
                    continue
                # Paced -- but if every pacing call in the loop is a fixed-
                # literal sleep and this is control-plane code, N retriers
                # re-arrive at the recovering apiserver in lockstep.
                if not _herd_scoped(ctx.path) or id(loop) in advised:
                    continue
                pacers = [n for s in loop.body for n in walk_fast(s)
                          if isinstance(n, ast.Call) and is_backoff_call(n)]
                if pacers and all(_constant_sleep(c) for c in pacers):
                    advised.add(id(loop))
                    findings.append(Finding(
                        "TJA018", "retry-backoff-no-jitter", ctx.path,
                        pacers[0].lineno, 0, WARNING,
                        f"retry loop in {fn.name}() paces every attempt "
                        f"with the same fixed sleep; under fleet-wide "
                        f"faults all retriers re-arrive in lockstep "
                        f"(thundering herd) -- use client/retry.py's "
                        f"jittered RetryPolicy or add jitter to the delay"))
    findings.sort(key=Finding.sort_key)
    return findings
