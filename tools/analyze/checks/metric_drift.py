"""TJA012 metric-name-drift: emitted Prometheus names vs the documented
registry.

Dashboards, alerts and runbooks are keyed on metric *names*; the code can
rename ``trainingjob_steps_stalled_total`` without any test noticing, and
every alert silently goes dark.  The authoritative registry is the metric
catalog table in ``docs/OBSERVABILITY.md`` (one backticked
``trainingjob_*`` name per row); this pass diffs it against every name the
package actually emits:

- **emitted-but-undocumented** (error, at the emission site): a literal
  ``trainingjob_*`` name is passed to a metric-shaped callee (``.inc`` /
  ``.observe`` / ``.gauge`` / ``.remove_gauge`` or a registration helper
  named like one) but has no catalog row;
- **documented-but-never-emitted** (warning, at the catalog row): a row
  names a metric nothing emits -- a stale doc or a rename that only
  landed in the code.

Dynamic names (f-strings, variables) are invisible and skipped; the
emitting modules keep names literal precisely so this pass can see them.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Tuple

from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project

DOC_REL = "docs/OBSERVABILITY.md"
METRIC_RE = re.compile(r"^trainingjob_[a-z0-9_]+$")
#: A catalog row: ``| `trainingjob_foo` | type | ...``.
ROW_RE = re.compile(r"^\|\s*`(trainingjob_[a-z0-9_]+)`\s*\|")
#: Callee leaf names that carry a metric name: the registry API itself
#: (``inc``/``observe``/``gauge``/``remove_gauge``) and the registration
#: helpers built on it (``_register_gauge_locked``, ``_has_gauge``).  A
#: metric-patterned literal passed anywhere *else* is not an emission --
#: e.g. the ``trainingjob_current_span`` ContextVar name in obs/trace.py.
EMIT_CALLEE_RE = re.compile(
    r"(inc|observe|gauge|counter|histogram|summary|metric)", re.IGNORECASE)


def _doc_registry(pc: ProjectContext) -> Dict[str, int]:
    """metric name -> line number of its catalog row."""
    path = os.path.join(pc.root, DOC_REL.replace("/", os.sep))
    out: Dict[str, int] = {}
    try:
        fh = open(path, "r", encoding="utf-8", errors="replace")
    except OSError:
        return out
    with fh:
        for i, line in enumerate(fh, start=1):
            m = ROW_RE.match(line.strip())
            if m:
                out.setdefault(m.group(1), i)
    return out


def _emitted(pc: ProjectContext) -> Dict[str, Tuple[str, int]]:
    """metric name -> first (path, line) where a literal name is passed to
    any call in the package (emission or registration)."""
    out: Dict[str, Tuple[str, int]] = {}
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or not rel.startswith("trainingjob_operator_tpu/"):
            continue
        for node in ctx.by_type(ast.Call):
            fn = node.func
            leaf = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if not EMIT_CALLEE_RE.search(leaf):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and METRIC_RE.match(arg.value)):
                    out.setdefault(arg.value, (rel, arg.lineno))
    return out


@register_project("TJA012", "metric-name-drift")
def check(pc: ProjectContext) -> List[Finding]:
    documented = _doc_registry(pc)
    if not documented:
        return []   # no registry to diff against (fixture trees)
    emitted = _emitted(pc)
    findings: List[Finding] = []
    for name in sorted(set(emitted) - set(documented)):
        path, line = emitted[name]
        findings.append(Finding(
            "TJA012", "metric-name-drift", path, line, 0, ERROR,
            f"metric {name!r} is emitted here but has no row in the "
            f"{DOC_REL} metric catalog; document it (dashboards and alerts "
            "are keyed on the registry)"))
    if not pc.covers_package("trainingjob_operator_tpu"):
        # "nothing emits it" is a whole-package claim; don't make it when
        # only a subset of the package was analyzed.
        findings.sort(key=Finding.sort_key)
        return findings
    for name in sorted(set(documented) - set(emitted)):
        findings.append(Finding(
            "TJA012", "metric-name-drift", DOC_REL, documented[name], 0,
            WARNING,
            f"metric {name!r} is documented in the catalog but nothing in "
            "the package emits it; delete the row or restore the emission"))
    findings.sort(key=Finding.sort_key)
    return findings
