"""Shared helpers for the path-sensitive passes (TJA015-TJA019).

Small, name-level classifiers (what blocks, what backs off, what is a lock)
plus lexical-scope utilities (parent chains, own-body walks that stop at
nested ``def``s).  Kept out of the individual check modules because TJA016
and TJA018 share the blocking/backoff vocabulary and all five share the
scope utilities.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from tools.analyze.findings import FileContext, _LOCAL_BARRIERS, _TOKEN_NODES
from tools.analyze.project import LOCK_FACTORIES

#: Method names that block unconditionally (socket/HTTP/process I/O).
BLOCKING_ATTRS = {"sleep", "sendall", "recv", "recvfrom", "accept",
                  "connect", "getresponse", "communicate", "select"}

#: Method names that block only when called with no positional argument and
#: no ``timeout=`` (reconcile-purity's unbounded-wait rule): ``lock.acquire()``
#: blocks, ``d.get(key)`` and ``",".join(parts)`` do not.
UNBOUNDED_ATTRS = {"wait", "join", "acquire", "get"}

#: Fully-dotted callables that block.
BLOCKING_DOTTED = {"time.sleep", "socket.create_connection",
                   "subprocess.run", "subprocess.check_output",
                   "subprocess.check_call", "select.select"}


def parents_of(ctx: FileContext) -> Dict[int, ast.AST]:
    """id(node) -> parent for every node in the file, recorded by the same
    single sweep that fills ``ctx.nodes`` (FileContext.parents)."""
    return ctx.parents


def enclosing(parents: Dict[int, ast.AST], node: ast.AST,
              *types: type) -> Optional[ast.AST]:
    """Nearest strict ancestor of ``node`` of one of ``types``."""
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, types):
            return cur
        cur = parents.get(id(cur))
    return None


def walk_local(root: ast.AST) -> Iterator[ast.AST]:
    """Every node lexically in ``root``'s body, *excluding* nested
    function/class bodies (deferred execution contexts) -- the same rule
    project.py's _BodyWalker applies.  ``root`` itself is not yielded.

    The node list is cached on ``root`` itself: seven call sites across the
    path-sensitive passes sweep the same functions, and re-walking each body
    per pass dominated the analyzer's --max-seconds budget.  For functions
    reached through a built FileContext the cache is already prefilled by
    ``FileContext._build_walk`` (same membership, BFS order instead of DFS
    -- every consumer is an order-blind classification scan); the lazy walk
    below only runs for ASTs parsed outside a FileContext (tests, ad-hoc
    fragments)."""
    cached = getattr(root, "_tja_local_walk", None)
    if cached is None:
        # Inlined iter_child_nodes with hoisted locals and the fields read
        # through ``__dict__`` (skips the descriptor machinery): this loop
        # runs once per node of every function body per run and is a
        # visible slice of the analyzer's wall-clock budget.
        cached = []
        isinst, AST, barriers = isinstance, ast.AST, _LOCAL_BARRIERS
        tokens = _TOKEN_NODES       # same prune as FileContext._build_walk
        stack = []
        push, pop, keep = stack.append, stack.pop, cached.append
        d = root.__dict__            # root itself: descend but do not yield
        for name in root._fields:
            v = d.get(name)
            if v.__class__ is list:
                for item in v:
                    if isinst(item, AST) and item.__class__ not in tokens:
                        push(item)
            elif isinst(v, AST) and v.__class__ not in tokens:
                push(v)
        while stack:
            node = pop()
            keep(node)
            if node.__class__ in barriers:
                continue
            d = node.__dict__
            for name in node._fields:
                v = d.get(name)
                if v.__class__ is list:
                    for item in v:
                        if isinst(item, AST) and item.__class__ not in tokens:
                            push(item)
                elif isinst(v, AST) and v.__class__ not in tokens:
                    push(v)
        root._tja_local_walk = cached
    return iter(cached)


def call_dotted(call: ast.Call) -> Optional[str]:
    """'time.sleep' / 'server.accept' / 'open' for a call's func chain.
    Memoized on the Call node: the blocking/backoff classifiers re-ask for
    the same calls across passes (~60% repeat rate under the lint budget)."""
    try:
        return call._tja_dotted
    except AttributeError:
        pass
    parts: List[str] = []
    node = call.func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    out = None
    if isinstance(node, ast.Name):
        parts.append(node.id)
        out = ".".join(reversed(parts))
    call._tja_dotted = out
    return out


def _has_timeout(call: ast.Call) -> bool:
    return (bool(call.args)
            or any(kw.arg == "timeout" and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None)
                for kw in call.keywords))


def blocking_reason(call: ast.Call) -> Optional[str]:
    """A short description when ``call`` is a blocking operation, else None.
    Purely name-level; callers layer interprocedural may-block on top."""
    dotted = call_dotted(call)
    if dotted in BLOCKING_DOTTED or dotted == "sleep":
        return dotted
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in BLOCKING_ATTRS:
            return f"{dotted or fn.attr}()"
        if fn.attr in UNBOUNDED_ATTRS and not _has_timeout(call):
            return f"unbounded {dotted or fn.attr}()"
    return None


def is_backoff_call(call: ast.Call) -> bool:
    """True for calls that pause before the next attempt: ``time.sleep``,
    bounded ``wait(timeout)``, or anything named like a backoff helper."""
    dotted = call_dotted(call) or ""
    leaf = dotted.rsplit(".", 1)[-1]
    if leaf == "sleep" or "backoff" in leaf.lower():
        return True
    if leaf == "wait" and isinstance(call.func, ast.Attribute) \
            and _has_timeout(call):
        return True
    return False


def local_lock_names(fn: ast.AST) -> Set[str]:
    """Names bound to ``threading.Lock()``-family factories in ``fn``'s own
    body (nested defs excluded) -- function-local and closure locks, which
    project.py summaries deliberately do not model."""
    out: Set[str] = set()
    for node in walk_local(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = None
            f = node.value.func
            if isinstance(f, ast.Name):
                name = f.id
            elif isinstance(f, ast.Attribute):
                name = f.attr
            if name in LOCK_FACTORIES:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def scope_lock_names(parents: Dict[int, ast.AST], fn: ast.AST) -> Set[str]:
    """Lock names visible to ``fn`` lexically: its own plus every enclosing
    function's (closures like ps_worker's ``handle``)."""
    out = local_lock_names(fn)
    cur = enclosing(parents, fn, ast.FunctionDef, ast.AsyncFunctionDef)
    while cur is not None:
        out |= local_lock_names(cur)
        cur = enclosing(parents, cur, ast.FunctionDef, ast.AsyncFunctionDef)
    return out


def functions_of(ctx: FileContext) -> List[ast.AST]:
    """Every function definition in the file, nested included (the shared
    by_type buckets make this a dict lookup, not a walk)."""
    return ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef)
