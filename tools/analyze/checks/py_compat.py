"""TJA001 py-compat: every file must parse under the oldest supported grammar.

We support Python 3.10+.  The seed's motivating bug: a backslash inside an
f-string replacement field (``f'{lbl(f"le=\\"{ub}\\"")}'``,
utils/metrics.py:147) is a SyntaxError on 3.10/3.11 but *legal* on 3.12+
(PEP 701) -- so a dev on 3.12 commits it green and every 3.10 runner fails at
import time, taking out all five test modules that transitively import the
controller package.

Two layers:

1. Parse gate -- the shared ``ast.parse`` already ran; a file that failed it
   is reported with the SyntaxError position.  On a 3.10 interpreter this is
   the full grammar check.
2. F-string backslash scan -- token-level, so it also fires when the analyzer
   itself runs on 3.12+ where the parse would succeed.
"""

from __future__ import annotations

import ast
import io
import sys
import tokenize
from typing import List, Tuple

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register

MIN_GRAMMAR = (3, 10)


def _string_prefix(tok_text: str) -> str:
    for i, ch in enumerate(tok_text):
        if ch in "\"'":
            return tok_text[:i].lower()
    return ""


def _body_of(tok_text: str) -> Tuple[str, int]:
    """(string body, offset of body start within the token text)."""
    prefix = len(_string_prefix(tok_text))
    rest = tok_text[prefix:]
    quote = rest[:3] if rest[:3] in ('"""', "'''") else rest[:1]
    return rest[len(quote):-len(quote)], prefix + len(quote)


def _scan_fstring_token(tok: tokenize.TokenInfo) -> List[Tuple[int, int]]:
    """Backslash positions inside replacement fields of one f-string token
    (pre-3.12 tokenizer: the whole literal is a single STRING token)."""
    body, body_off = _body_of(tok.string)
    hits: List[Tuple[int, int]] = []
    depth = 0
    line, col = tok.start[0], tok.start[1] + body_off
    i = 0
    while i < len(body):
        ch = body[i]
        nxt = body[i + 1] if i + 1 < len(body) else ""
        if ch in "{}" and nxt == ch:       # literal {{ or }}
            i, col = i + 2, col + 2
            continue
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth = max(depth - 1, 0)
        elif ch == "\\" and depth > 0:
            hits.append((line, col))
        if ch == "\n":
            line, col = line + 1, 0
        else:
            col += 1
        i += 1
    return hits


def _fstring_backslash_positions(source: str) -> List[Tuple[int, int]]:
    hits: List[Tuple[int, int]] = []
    if "\\" not in source:
        return hits   # no backslash anywhere: skip the (costly) tokenize
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return hits  # unreadable source: the parse gate already reported it
    fstring_start = getattr(tokenize, "FSTRING_START", None)
    fstring_parts = {t for t in (fstring_start,
                                 getattr(tokenize, "FSTRING_MIDDLE", None),
                                 getattr(tokenize, "FSTRING_END", None))
                     if t is not None}
    depth = 0
    for tok in tokens:
        if tok.type == tokenize.STRING and "f" in _string_prefix(tok.string):
            hits.extend(_scan_fstring_token(tok))
        elif fstring_start is not None:
            # 3.12+ tokenizer: expression tokens stream between START/END.
            if tok.type == fstring_start:
                depth += 1
            elif tok.type == getattr(tokenize, "FSTRING_END", -1):
                depth = max(depth - 1, 0)
            elif (depth > 0 and tok.type not in fstring_parts
                  and "\\" in tok.string):
                hits.append(tok.start)
    return hits


@register("TJA001", "py-compat")
def check(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    if ctx.tree is None:
        try:
            compile(ctx.source, ctx.path, "exec", dont_inherit=True)
            line, col, msg = 1, 0, "file does not parse"
        except SyntaxError as exc:
            line, col = exc.lineno or 1, (exc.offset or 1) - 1
            msg = exc.msg or "syntax error"
        findings.append(Finding(
            "TJA001", "py-compat", ctx.path, line, col, ERROR,
            f"does not parse under Python "
            f"{MIN_GRAMMAR[0]}.{MIN_GRAMMAR[1]} grammar: {msg}"))
        return findings
    if sys.version_info < (3, 12):
        # The file parsed under this interpreter, and before 3.12 a
        # backslash inside a replacement field IS a SyntaxError -- the
        # token scan cannot find anything the parse gate didn't.  It only
        # earns its keep (and its tokenize cost) on 3.12+, where PEP 701
        # makes the parse succeed.
        return findings
    if "\\" not in ctx.source or not ctx.by_type(ast.JoinedStr):
        return findings   # no f-string + backslash combo: skip the tokenize
    # Second gate: only tokenize when a backslash falls within some
    # f-string's own line span.  Most files that pass the first gate have
    # their backslashes in ordinary strings/continuations, nowhere near an
    # f-string -- a line-span scan is ~free, a full tokenize is not.
    lines = ctx.source.split("\n")
    if not any("\\" in line
               for n in ctx.by_type(ast.JoinedStr)
               for line in lines[n.lineno - 1:(n.end_lineno or n.lineno)]):
        return findings
    for line, col in _fstring_backslash_positions(ctx.source):
        findings.append(Finding(
            "TJA001", "py-compat", ctx.path, line, col, ERROR,
            "backslash inside f-string replacement field is a SyntaxError "
            f"before Python 3.12 (oldest supported grammar is "
            f"{MIN_GRAMMAR[0]}.{MIN_GRAMMAR[1]}); hoist the escaped text "
            "into a variable"))
    return findings
