"""Analyzer passes.  Each module @registers itself with the runner."""
