"""TJA010 lock-order-cycle: a whole-program lock-acquisition-order graph.

The reconcile plane holds locks across call boundaries: the telemetry
aggregator registers gauges in the metrics registry while holding its own
lock, the workqueue's condition feeds worker threads that re-enter the
tracker, mixins acquire attributes their siblings created.  Per-file passes
(TJA002) can prove *discipline* -- mutations happen under the lock -- but
only a global view can prove *order*: if thread A takes L1 then L2 while
thread B takes L2 then L1, the process deadlocks the first time the
schedules interleave, typically weeks into a soak run.

The pass builds a directed graph over every lock in the project (class
attributes assigned ``threading.Lock()``/``RLock()``/``Condition()`` --
identified by their *creating* class, so mixin siblings share one node --
plus module-level locks).  An edge L1 -> L2 is added when some method:

- acquires L2 (``with``/``.acquire()``) lexically inside a ``with L1:``; or
- calls, while holding L1, a callable that (transitively, through the
  project call graph: ``self.m()`` across mixin MROs, module functions,
  ``self._attr.m()`` / ``GLOBAL.m()`` via inferred constructor types) may
  acquire L2.

Any cycle is a potential deadlock and is reported once, with the witness
edge sites.  A self-cycle (re-acquiring a lock already held) is reported
only for non-reentrant ``Lock``s -- ``RLock``/``Condition`` re-entry is
legal.  Dynamic dispatch and callbacks are invisible; this is a
conservative witness-based pass, not a proof of absence.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import (
    ClassInfo, MethodSummary, ModuleInfo, ProjectContext, REENTRANT_FACTORIES,
)
from tools.analyze.runner import register_project


class _Resolver:
    """Resolution helpers shared by the graph build, with caches."""

    def __init__(self, pc: ProjectContext):
        self.pc = pc
        self._composites: Dict[str, List[ClassInfo]] = {}
        self._creator: Dict[Tuple[str, str], Optional[Tuple[str, str]]] = {}

    def composites(self, ci: ClassInfo) -> List[ClassInfo]:
        got = self._composites.get(ci.qual)
        if got is None:
            got = self.pc.subclasses_including(ci)
            self._composites[ci.qual] = got
        return got

    def lock_id(self, mod: ModuleInfo, cls: Optional[ClassInfo],
                name: str) -> Optional[Tuple[str, str]]:
        """(lock id, factory kind) for a raw acquisition name recorded in a
        summary: a module-level lock, or a ``self.X`` attribute whose
        creating class is found in the MRO of any composite the defining
        class is mixed into.  None when the name is not provably a lock."""
        if name in mod.module_locks:
            return f"{mod.name}.{name}", mod.module_locks[name]
        if cls is None:
            return None
        key = (cls.qual, name)
        if key in self._creator:
            return self._creator[key]
        found: Optional[Tuple[str, str]] = None
        for k in [cls] + self.composites(cls):
            for c in self.pc.mro_classes(k):
                if name in c.lock_attrs:
                    found = (f"{c.qual}.{name}", c.lock_attrs[name])
                    break
            if found:
                break
        self._creator[key] = found
        return found

    def callee_summaries(self, mod: ModuleInfo, cls: Optional[ClassInfo],
                         callee: tuple) -> List[Tuple[ModuleInfo,
                                                      Optional[ClassInfo],
                                                      MethodSummary]]:
        kind = callee[0]
        out: List[Tuple[ModuleInfo, Optional[ClassInfo], MethodSummary]] = []
        if kind == "self" and cls is not None:
            name = callee[1]
            seen: Set[str] = set()
            for k in self.composites(cls):
                table = self.pc.mro_methods(k)
                hit = table.get(name)
                if hit is None:
                    continue
                ci, _node = hit
                s = ci.summaries.get(name)
                if s is not None and s.qual not in seen:
                    seen.add(s.qual)
                    out.append((self.pc.modules[ci.module], ci, s))
            return out
        if kind == "name":
            name = callee[1]
            if name in mod.fn_summaries:
                return [(mod, None, mod.fn_summaries[name])]
            target = mod.imports.get(name)
            if target:
                tmod, _, leaf = target.rpartition(".")
                mi = self.pc.modules.get(tmod)
                if mi is not None and leaf in mi.fn_summaries:
                    return [(mi, None, mi.fn_summaries[leaf])]
            return out
        if kind == "attr":
            leaf, meth = callee[1], callee[2]
            ctor: Optional[Tuple[str, str]] = None   # (module, class name)
            if cls is not None:
                for k in [cls] + self.composites(cls):
                    for c in self.pc.mro_classes(k):
                        if leaf in c.attr_ctors:
                            ctor = (c.module, c.attr_ctors[leaf])
                            break
                    if ctor:
                        break
            if ctor is None:
                tgt, src_mod = mod.global_ctors.get(leaf), mod.name
                if tgt is None:
                    imp = mod.imports.get(leaf)
                    if imp:
                        m, _, l2 = imp.rpartition(".")
                        mi = self.pc.modules.get(m)
                        if mi is not None and l2 in mi.global_ctors:
                            tgt, src_mod = mi.global_ctors[l2], m
                if tgt is not None:
                    ctor = (src_mod, tgt)
            if ctor is not None:
                ci = self.pc.resolve_class(ctor[0], ctor[1])
                if ci is not None:
                    table = self.pc.mro_methods(ci)
                    hit = table.get(meth)
                    if hit is not None:
                        c2, _node = hit
                        s = c2.summaries.get(meth)
                        if s is not None:
                            out.append((self.pc.modules[c2.module], c2, s))
            return out
        return out


def _iter_summaries(pc: ProjectContext):
    for mod in pc.modules.values():
        for s in mod.fn_summaries.values():
            yield mod, None, s
        for ci in mod.classes.values():
            for s in ci.summaries.values():
                yield mod, ci, s


@register_project("TJA010", "lock-order-cycle")
def check(pc: ProjectContext) -> List[Finding]:
    res = _Resolver(pc)

    # Per-summary facts: resolved direct lock ids + resolved callee quals.
    direct: Dict[str, Set[str]] = {}
    callees: Dict[str, Set[str]] = {}
    ctx_of: Dict[str, Tuple[ModuleInfo, Optional[ClassInfo], MethodSummary]] = {}
    kinds: Dict[str, str] = {}
    for mod, cls, s in _iter_summaries(pc):
        ctx_of[s.qual] = (mod, cls, s)
        locks: Set[str] = set()
        for name in s.acquires:
            hit = res.lock_id(mod, cls, name)
            if hit is not None:
                locks.add(hit[0])
                kinds[hit[0]] = hit[1]
        direct[s.qual] = locks
        outs: Set[str] = set()
        for call in {c[:-1] for c in s.calls}:   # drop lineno, dedup
            for _m, _c, cs in res.callee_summaries(mod, cls, call):
                outs.add(cs.qual)
        callees[s.qual] = outs

    # Transitive may-acquire, by fixpoint over the (small) call graph.
    may: Dict[str, Set[str]] = {q: set(v) for q, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for q, outs in callees.items():
            acc = may[q]
            before = len(acc)
            for o in outs:
                acc |= may.get(o, set())
            if len(acc) != before:
                changed = True

    # Lock-order edges, with one witness (path, line, holder qual) each.
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(l1: str, l2: str, mod: ModuleInfo, line: int,
                 qual: str) -> None:
        edges.setdefault((l1, l2), (mod.ctx.path, line, qual))

    for qual, (mod, cls, s) in ctx_of.items():
        for outer, inner, line in s.nested_acquires:
            h1, h2 = res.lock_id(mod, cls, outer), res.lock_id(mod, cls, inner)
            if h1 and h2:
                add_edge(h1[0], h2[0], mod, line, qual)
        for outer, callee, line in s.held_calls:
            h1 = res.lock_id(mod, cls, outer)
            if h1 is None:
                continue
            for _m, _c, cs in res.callee_summaries(mod, cls, callee):
                for l2 in may.get(cs.qual, ()):
                    add_edge(h1[0], l2, mod, line, qual)

    findings: List[Finding] = []

    # Self-cycles: re-acquiring a non-reentrant Lock already held.
    for (l1, l2), (path, line, qual) in sorted(edges.items()):
        if l1 == l2 and kinds.get(l1) not in REENTRANT_FACTORIES:
            findings.append(Finding(
                "TJA010", "lock-order-cycle", path, line, 0, ERROR,
                f"{qual} may re-acquire non-reentrant lock {l1} while "
                f"already holding it (self-deadlock); use an RLock or hoist "
                f"the inner acquisition out of the locked region"))

    # Multi-lock cycles: DFS over the order graph.
    graph: Dict[str, List[str]] = {}
    for (l1, l2) in edges:
        if l1 != l2:
            graph.setdefault(l1, []).append(l2)
    for outs in graph.values():
        outs.sort()

    reported: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in graph.get(node, ()):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key in reported:
                    continue
                reported.add(key)
                cycle = path + [start]
                hops = []
                for a, b in zip(cycle, cycle[1:]):
                    p, ln, q = edges[(a, b)]
                    hops.append(f"{a} -> {b} ({q} at {p}:{ln})")
                p0, ln0, _q0 = edges[(cycle[0], cycle[1])]
                findings.append(Finding(
                    "TJA010", "lock-order-cycle", p0, ln0, 0, ERROR,
                    "lock-order cycle (potential deadlock): "
                    + "; ".join(hops)
                    + "; impose one global acquisition order or drop a lock "
                      "before crossing the boundary"))
            elif nxt not in path and nxt > start:
                # Only explore nodes > start so each cycle is found from its
                # smallest member exactly once.
                dfs(start, nxt, path + [nxt])

    for node in sorted(graph):
        dfs(node, node, [node])

    findings.sort(key=Finding.sort_key)
    return findings
