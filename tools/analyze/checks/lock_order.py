"""TJA010 lock-order-cycle: a whole-program lock-acquisition-order graph.

The reconcile plane holds locks across call boundaries: the telemetry
aggregator registers gauges in the metrics registry while holding its own
lock, the workqueue's condition feeds worker threads that re-enter the
tracker, mixins acquire attributes their siblings created.  Per-file passes
(TJA002) can prove *discipline* -- mutations happen under the lock -- but
only a global view can prove *order*: if thread A takes L1 then L2 while
thread B takes L2 then L1, the process deadlocks the first time the
schedules interleave, typically weeks into a soak run.

The pass builds a directed graph over every lock in the project (class
attributes assigned ``threading.Lock()``/``RLock()``/``Condition()`` --
identified by their *creating* class, so mixin siblings share one node --
plus module-level locks).  An edge L1 -> L2 is added when some method:

- acquires L2 (``with``/``.acquire()``) lexically inside a ``with L1:``; or
- calls, while holding L1, a callable that (transitively, through the
  project call graph: ``self.m()`` across mixin MROs, module functions,
  ``self._attr.m()`` / ``GLOBAL.m()`` via inferred constructor types) may
  acquire L2.

Any cycle is a potential deadlock and is reported once, with the witness
edge sites.  A self-cycle (re-acquiring a lock already held) is reported
only for non-reentrant ``Lock``s -- ``RLock``/``Condition`` re-entry is
legal.  Dynamic dispatch and callbacks are invisible; this is a
conservative witness-based pass, not a proof of absence.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import (
    CallResolver, ClassInfo, MethodSummary, ModuleInfo, ProjectContext,
    REENTRANT_FACTORIES,
)
from tools.analyze.runner import register_project

#: The resolver grew up here; it now lives in project.py so the thread-model
#: layer shares the same callee/lock resolution (and caches).
_Resolver = CallResolver


def _iter_summaries(pc: ProjectContext):
    for mod in pc.modules.values():
        for s in mod.fn_summaries.values():
            yield mod, None, s
        for ci in mod.classes.values():
            for s in ci.summaries.values():
                yield mod, ci, s


@register_project("TJA010", "lock-order-cycle")
def check(pc: ProjectContext) -> List[Finding]:
    res = _Resolver(pc)

    # Per-summary facts: resolved direct lock ids + resolved callee quals.
    direct: Dict[str, Set[str]] = {}
    callees: Dict[str, Set[str]] = {}
    ctx_of: Dict[str, Tuple[ModuleInfo, Optional[ClassInfo], MethodSummary]] = {}
    kinds: Dict[str, str] = {}
    for mod, cls, s in _iter_summaries(pc):
        ctx_of[s.qual] = (mod, cls, s)
        locks: Set[str] = set()
        for name in s.acquires:
            hit = res.lock_id(mod, cls, name)
            if hit is not None:
                locks.add(hit[0])
                kinds[hit[0]] = hit[1]
        direct[s.qual] = locks
        outs: Set[str] = set()
        for call in {c[:-1] for c in s.calls}:   # drop lineno, dedup
            for _m, _c, cs in res.callee_summaries(mod, cls, call):
                outs.add(cs.qual)
        callees[s.qual] = outs

    # Transitive may-acquire, by fixpoint over the (small) call graph.
    may: Dict[str, Set[str]] = {q: set(v) for q, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for q, outs in callees.items():
            acc = may[q]
            before = len(acc)
            for o in outs:
                acc |= may.get(o, set())
            if len(acc) != before:
                changed = True

    # Lock-order edges, with one witness (path, line, holder qual) each.
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(l1: str, l2: str, mod: ModuleInfo, line: int,
                 qual: str) -> None:
        edges.setdefault((l1, l2), (mod.ctx.path, line, qual))

    for qual, (mod, cls, s) in ctx_of.items():
        for outer, inner, line in s.nested_acquires:
            h1, h2 = res.lock_id(mod, cls, outer), res.lock_id(mod, cls, inner)
            if h1 and h2:
                add_edge(h1[0], h2[0], mod, line, qual)
        for outer, callee, line in s.held_calls:
            h1 = res.lock_id(mod, cls, outer)
            if h1 is None:
                continue
            for _m, _c, cs in res.callee_summaries(mod, cls, callee):
                for l2 in may.get(cs.qual, ()):
                    add_edge(h1[0], l2, mod, line, qual)

    findings: List[Finding] = []

    # Self-cycles: re-acquiring a non-reentrant Lock already held.
    for (l1, l2), (path, line, qual) in sorted(edges.items()):
        if l1 == l2 and kinds.get(l1) not in REENTRANT_FACTORIES:
            findings.append(Finding(
                "TJA010", "lock-order-cycle", path, line, 0, ERROR,
                f"{qual} may re-acquire non-reentrant lock {l1} while "
                f"already holding it (self-deadlock); use an RLock or hoist "
                f"the inner acquisition out of the locked region"))

    # Multi-lock cycles: DFS over the order graph.
    graph: Dict[str, List[str]] = {}
    for (l1, l2) in edges:
        if l1 != l2:
            graph.setdefault(l1, []).append(l2)
    for outs in graph.values():
        outs.sort()

    reported: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in graph.get(node, ()):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key in reported:
                    continue
                reported.add(key)
                cycle = path + [start]
                hops = []
                for a, b in zip(cycle, cycle[1:]):
                    p, ln, q = edges[(a, b)]
                    hops.append(f"{a} -> {b} ({q} at {p}:{ln})")
                p0, ln0, _q0 = edges[(cycle[0], cycle[1])]
                findings.append(Finding(
                    "TJA010", "lock-order-cycle", p0, ln0, 0, ERROR,
                    "lock-order cycle (potential deadlock): "
                    + "; ".join(hops)
                    + "; impose one global acquisition order or drop a lock "
                      "before crossing the boundary"))
            elif nxt not in path and nxt > start:
                # Only explore nodes > start so each cycle is found from its
                # smallest member exactly once.
                dfs(start, nxt, path + [nxt])

    for node in sorted(graph):
        dfs(node, node, [node])

    findings.sort(key=Finding.sort_key)
    return findings
