"""TJA028 unguarded-shared-state: MHP-aware static race detection.

TJA002 proves lock *discipline* (an attribute guarded somewhere is
guarded everywhere) but says nothing about state that is never guarded
at all -- and it has no notion of which threads actually run.  This
pass closes that gap with the thread-model layer: two roles that may
happen in parallel (MHP) touching the same shared object, at least one
touch a write, and **disjoint lock-sets** at the two sites, is a data
race the schedules will eventually find.

Two object universes, both witness-based:

- **module-global bare containers** (dicts/lists/sets/deques/counters
  from the TJA027 inventory -- class-instance singletons own their
  locking and are vetted by TJA032 instead);
- **shared instance container attributes**: ``self.X = {}``-style attrs
  whose owning class's methods are split across MHP roles (a runtime
  poller thread and the reconcile worker that owns the runtime, say).
  ``__init__`` writes are exempt -- construction happens-before any
  spawn.

The witness names both access chains (role, site, via, lock-set) and
both spawn sites, so a reader can replay the interleaving.  A role
whose closure does not reach the object contributes nothing; unreached
code (CLI-only, test-only) never produces evidence.  GIL-atomic
single-op patterns that are *deliberately* lock-free (monotonic stats
counters read without the lock) are expected to carry a waiver naming
that reasoning -- the waiver inventory lives in
docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from tools.analyze import threadmodel
from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project
from tools.analyze.threadmodel import Access, ThreadModel

CHECK_ID, CHECK_NAME = "TJA028", "unguarded-shared-state"


def _witness_pair(tm: ThreadModel, accesses: List[Access]) \
        -> Optional[Tuple[Access, str, Access, str]]:
    """First (write access, role, other access, role) pair that is MHP
    with disjoint lock-sets, or None.  Lock-sets are computed lazily and
    only for role-reaching accesses."""
    enriched = []
    for a in sorted(accesses, key=lambda a: (a.path, a.line, a.via)):
        if threadmodel.locked_by_convention(a.qual):
            continue   # *_locked methods run with the owner's lock held
        roles = sorted(tm.roles_of(a.qual))
        if roles:
            enriched.append((a, roles))
    locks: Dict[Tuple[str, int], frozenset] = {}

    def lock_set(a: Access) -> frozenset:
        key = (a.path, a.line)
        got = locks.get(key)
        if got is None:
            got = tm.lock_set(a.path, a.line)
            locks[key] = got
        return got

    for i, (a1, roles1) in enumerate(enriched):
        for a2, roles2 in enriched[i:]:
            if not (a1.write or a2.write):
                continue
            pair = None
            for ra in roles1:
                for rb in roles2:
                    if a1 is a2 and ra == rb:
                        # the same site racing itself needs two instances
                        if tm.mhp(ra, ra):
                            pair = (ra, rb)
                    elif tm.mhp(ra, rb):
                        pair = (ra, rb)
                    if pair:
                        break
                if pair:
                    break
            if pair is None:
                continue
            if lock_set(a1) & lock_set(a2):
                continue
            if a1.write:
                return a1, pair[0], a2, pair[1]
            return a2, pair[1], a1, pair[0]
    return None


def _spawn_site(tm: ThreadModel, role: str) -> str:
    r = tm.roles.get(role)
    if r is None or not r.spawn_path:
        return role
    return f"{r.spawn_path}:{r.spawn_line}"


def _describe(tm: ThreadModel, a: Access, role: str) -> str:
    locks = sorted(tm.lock_set(a.path, a.line))
    held = "{" + ", ".join(locks) + "}" if locks else "no lock"
    return (f"{'written' if a.write else 'read'} ({a.via}) at "
            f"{a.path}:{a.line} by role {role} "
            f"(spawned {_spawn_site(tm, role)}) under {held}")


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    tm = threadmodel.model(pc)
    if not any(r.kind == "thread" for r in tm.roles.values()):
        return []
    findings: List[Finding] = []

    # Module-global bare containers, from the shard-state inventory.
    from tools.analyze.checks import shard_state
    inventory, _reg, _lines, _rl = shard_state.build(pc)
    for key, s in sorted(inventory.items()):
        if s.kind not in threadmodel.BARE_CONTAINER_KINDS:
            continue
        accesses = [Access(path=p, line=ln, via=via, write=True,
                           qual=tm.owner_qual(p, ln))
                    for p, ln, via in s.writes]
        accesses += [Access(path=p, line=ln, via=via, write=False,
                            qual=tm.owner_qual(p, ln))
                     for p, ln, via in s.reads]
        hit = _witness_pair(tm, accesses)
        if hit is None:
            continue
        w, wrole, o, orole = hit
        findings.append(Finding(
            CHECK_ID, CHECK_NAME, w.path, w.line, 0, ERROR,
            f"module-global {key!r} ({s.kind}) is shared across "
            f"may-happen-in-parallel threads with disjoint lock-sets: "
            f"{_describe(tm, w, wrole)}; also "
            f"{_describe(tm, o, orole)}; guard both sites under one lock "
            "or make the state role-local"))

    # Shared instance container attributes.
    for (cls_qual, attr), accesses in sorted(tm.attr_accesses().items()):
        hit = _witness_pair(tm, accesses)
        if hit is None:
            continue
        w, wrole, o, orole = hit
        findings.append(Finding(
            CHECK_ID, CHECK_NAME, w.path, w.line, 0, ERROR,
            f"instance attribute {cls_qual}.{attr} is shared across "
            f"may-happen-in-parallel threads with disjoint lock-sets: "
            f"{_describe(tm, w, wrole)}; also "
            f"{_describe(tm, o, orole)}; guard both sites under one lock "
            "or make the state role-local"))

    findings.sort(key=Finding.sort_key)
    return findings
