"""TJA020 recompile-hazard: traced call sites that retrigger compilation.

The serving plane's headline claim ("three traced executables total, no
admission-pattern recompiles", docs/SERVING.md) and the step-loop goodput
math both die quietly when a jit boundary sees a new shape, a new static
value, or a brand-new wrapper object.  Three syntactic shapes cover the
regressions the bench gates have actually caught:

- **wrapper built per iteration**: ``jax.jit(...)`` constructed inside a
  loop (or inside a function that runs once per hot-loop tick) misses the
  jit cache -- every pass traces and compiles from scratch;
- **runtime-varying statics**: a ``static_argnums``/``static_argnames``
  argument fed ``len(queue)``-shaped values compiles one executable per
  distinct value; a list/dict/set literal is not even hashable and fails
  at dispatch;
- **unpadded slices**: a traced operand built from a runtime-bound slice
  (``prompt[pos:pos+n]`` with non-constant bounds) changes shape per call,
  and every shape is a fresh compile.  Pad to a fixed shape (serve.py's
  prefill chunk is the exemplar).

Every finding names the varying source and the jit site it hits, via the
memoized ``jit_boundary`` layer.  ``tests/`` are exempt: tests compile on
purpose.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.analyze import jit_boundary as jb
from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project


def _short(qual: str) -> str:
    return qual.rsplit(".", 1)[-1]


def _is_test_path(path: str) -> bool:
    return path.startswith("tests/") or "/tests/" in path


def _loop_assigned(rec: jb.FnRec) -> Set[str]:
    """Names (re)bound somewhere under a loop in this scope."""
    out: Set[str] = set()
    for loop in rec.loops:
        for node in ast.walk(loop):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.For, ast.NamedExpr)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
    return out


def _src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except (ValueError, AttributeError):
        return "<expr>"


@register_project("TJA020", "recompile-hazard")
def check(pc: ProjectContext) -> List[Finding]:
    b = jb.boundary(pc)
    findings: List[Finding] = []

    def emit(path: str, line: int, col: int, sev: str, msg: str) -> None:
        findings.append(Finding("TJA020", "recompile-hazard", path, line,
                                col, sev, msg))

    # Wrapper objects constructed per iteration / per tick.
    for site in b.sites:
        if site.kind in ("scan", "decorator") or _is_test_path(site.path):
            continue
        if site.wrap_in_loop:
            emit(site.path, site.line, site.col, ERROR,
                 f"jax.{site.kind} wrapper constructed inside a loop; each "
                 "iteration builds a fresh wrapper, misses the jit cache "
                 "and re-traces/recompiles -- hoist the wrapper out of the "
                 "loop")
        elif site.owner_qual in b.hot_fns:
            hl = b.hot_fns[site.owner_qual]
            emit(site.path, site.line, site.col, ERROR,
                 f"jax.{site.kind} wrapper constructed in "
                 f"'{_short(site.owner_qual)}', which runs every iteration "
                 f"of the {hl.describe()}; each tick compiles a new "
                 "executable -- build it once at init")

    # Call-site hazards against known jitted bindings.
    for qual, rec in b.fns.items():
        if _is_test_path(rec.path):
            continue
        loop_names: Set[str] = set()
        loop_names_built = False
        for cr in rec.calls:
            site = b.site_for_call(rec, cr)
            if site is None:
                continue
            call = cr.node

            def static_arg(arg: ast.expr, what: str) -> None:
                if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    emit(rec.path, arg.lineno, arg.col_offset, ERROR,
                         f"non-hashable {arg.__class__.__name__.lower()} "
                         f"literal passed as {what} to the "
                         f"{site.describe()}; static arguments must be "
                         "hashable (tuple it) or the dispatch raises")
                    return
                for n in ast.walk(arg):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Name)
                            and n.func.id == "len"):
                        emit(rec.path, n.lineno, n.col_offset, WARNING,
                             f"'{_src(n)}' feeds {what} of the "
                             f"{site.describe()}; every distinct length "
                             "compiles a new executable -- pad/bucket it "
                             "or pass it traced")
                        return
                nonlocal loop_names_built, loop_names
                if isinstance(arg, ast.Name) and cr.loop_stack:
                    if not loop_names_built:
                        loop_names = _loop_assigned(rec)
                        loop_names_built = True
                    if arg.id in loop_names:
                        emit(rec.path, arg.lineno, arg.col_offset, WARNING,
                             f"loop-varying '{arg.id}' feeds {what} of the "
                             f"{site.describe()}; each new value is a "
                             "cache miss and a recompile inside the loop")

            for idx in site.static_argnums:
                if idx < len(call.args):
                    static_arg(call.args[idx], f"static_argnums[{idx}]")
            for kw in call.keywords:
                if kw.arg and kw.arg in site.static_argnames:
                    static_arg(kw.value, f"static_argnames '{kw.arg}'")

            # Traced (non-static) operands built from runtime-bound slices.
            for i, arg in enumerate(call.args):
                if i in site.static_argnums:
                    continue
                for n in ast.walk(arg):
                    if not (isinstance(n, ast.Subscript)
                            and isinstance(n.slice, ast.Slice)):
                        continue
                    bounds = [x for x in (n.slice.lower, n.slice.upper)
                              if x is not None]
                    if bounds and not all(isinstance(x, ast.Constant)
                                          for x in bounds):
                        emit(rec.path, n.lineno, n.col_offset, WARNING,
                             f"traced operand '{_src(n)}' takes a "
                             "runtime-bound slice; its shape varies per "
                             f"call into the {site.describe()} and every "
                             "shape recompiles -- pad to a fixed shape "
                             "first")
                        break

    findings.sort(key=Finding.sort_key)
    return findings
