"""TJA005 constant-drift: the label/annotation/env contract lives in
``api/constants.py`` -- nowhere else.

The operator's contract with workloads is a set of magic strings: pod label
keys, annotation keys, and injected env-var names (``TPU_WORKER_ID``, the
``TRAININGJOB_*`` identity set, ``MEGASCALE_*`` rendezvous hosts).  A typo'd
inline copy in ``controller/``/``runtime/``/``workloads/`` doesn't fail --
it silently mismatches: the pod gets one label, the selector looks for
another, and reconcile sees orphans.  Two failure shapes are flagged:

1. an inline literal exactly equal to a constant defined in
   ``api/constants.py`` (use the constant); and
2. a new ``TRAININGJOB_*`` / ``TPU_WORKER_*`` / ``MEGASCALE_*`` contract
   string that is *not* defined there (define it first).

Only "contract-shaped" constants participate in (1): values containing an
upper-case letter, a dot, or a slash.  Generic lowercase words
(``"priority"``) would otherwise flood the pass with coincidences.
Docstrings and f-string literal segments are skipped.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register

SCOPE_DIRS = ("/controller/", "/runtime/", "/workloads/")
CONSTANTS_REL = "trainingjob_operator_tpu/api/constants.py"
CONTRACT_ENV_RE = re.compile(
    r"^(TRAININGJOB_[A-Z0-9_]+|TPU_WORKER_[A-Z0-9_]+|MEGASCALE_[A-Z0-9_]+)$")

_cache: Dict[str, Tuple[float, Dict[str, str], Set[str]]] = {}


def _contract_shaped(value: str) -> bool:
    return bool(re.search(r"[A-Z./]", value)) and 3 <= len(value) <= 120


def _load_constants(repo_root: str) -> Tuple[Dict[str, str], Set[str]]:
    """(value -> constant name) plus the set of every defined string value
    (including non-contract-shaped ones, for pattern check 2)."""
    path = os.path.join(repo_root, CONSTANTS_REL)
    # One stat, not an exists + getmtime pair: this runs once per analyzed
    # file and stat latency is a visible slice of the --max-seconds budget.
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}, set()
    cached = _cache.get(path)
    if cached and cached[0] == mtime:
        return cached[1], cached[2]
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    by_value: Dict[str, str] = {}
    all_values: Set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        name = node.targets[0].id if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)) else None
        if name is None:
            continue
        values: List[str] = []
        if isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
            values = [node.value.value]
        elif isinstance(node.value, (ast.Tuple, ast.List)):
            values = [el.value for el in node.value.elts
                      if isinstance(el, ast.Constant)
                      and isinstance(el.value, str)]
        elif isinstance(node.value, ast.JoinedStr):
            # e.g. API_VERSION = f"{GROUP_NAME}/{GROUP_VERSION}" -- the value
            # is derived; skip rather than evaluate.
            continue
        for v in values:
            all_values.add(v)
            if _contract_shaped(v) and v not in by_value:
                by_value[v] = name
    _cache[path] = (mtime, by_value, all_values)
    return by_value, all_values


def _docstring_and_fstring_nodes(nodes: list) -> Set[int]:
    skip: Set[int] = set()
    for node in nodes:
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                skip.add(id(body[0].value))
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.Constant):
                    skip.add(id(part))
    return skip


def _repo_root(ctx: FileContext) -> Optional[str]:
    # abs_path ends with the repo-relative path; strip it off.
    suffix = ctx.path.replace("/", os.sep)
    if ctx.abs_path.endswith(suffix):
        return ctx.abs_path[:-len(suffix)].rstrip(os.sep) or os.sep
    return None


@register("TJA005", "constant-drift")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    marked = f"/{ctx.path}"
    if not any(d in marked for d in SCOPE_DIRS):
        return []
    root = _repo_root(ctx)
    if root is None:
        return []
    by_value, all_values = _load_constants(root)
    if not by_value and not all_values:
        return []
    skip = _docstring_and_fstring_nodes(ctx.by_type(
        ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef,
        ast.JoinedStr))
    findings: List[Finding] = []
    for node in ctx.by_type(ast.Constant):
        if not isinstance(node.value, str):
            continue
        if id(node) in skip:
            continue
        value = node.value
        const_name = by_value.get(value)
        if const_name is not None:
            findings.append(Finding(
                "TJA005", "constant-drift", ctx.path, node.lineno,
                node.col_offset, ERROR,
                f"inline literal {value!r} duplicates "
                f"api/constants.py:{const_name}; import the constant "
                "(a typo'd copy silently breaks the label/env contract)"))
        elif CONTRACT_ENV_RE.match(value) and value not in all_values:
            findings.append(Finding(
                "TJA005", "constant-drift", ctx.path, node.lineno,
                node.col_offset, ERROR,
                f"contract env var {value!r} is not defined in "
                "api/constants.py; define it there and import it"))
    return findings
