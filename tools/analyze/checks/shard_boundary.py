"""TJA032 shard-boundary-discipline: hold the shard-state registry's
claims against the thread model.

TJA027 checks that ``SHARD_STATE_REGISTRY`` (api/constants.py) is
*complete* -- every module-level mutable singleton is classified.  This
pass checks that the classifications are *true*, now that the thread
model knows which roles touch what under which locks:

- ``lock_guarded_shared`` means "threads coordinate via a witnessed
  lock".  A bare-container singleton accessed from inside a function
  with **no lock held at the site** breaks the claim (import-time init
  runs before any thread exists and is exempt).  A class-instance
  singleton keeps the claim if the mutating call site either holds a
  lock or goes through a method whose closure provably acquires one
  (the usual ``TRACER.record()`` -> ``with self._lock`` shape).

- ``shard_local`` means "each shard owns its keys' slice" -- which
  presumes *within* a process the keyed accesses are coherent.  When
  two may-happen-in-parallel roles both mutate the singleton and some
  mutating site holds no lock, the per-key story needs a witness the
  model cannot see; the definition gets an ERROR (genuinely per-thread
  keyed maps carry a waiver naming the keying argument).

- a ``global X`` **rebind** executed inside any spawned role must name
  classified state: an undeclared process-global written from
  concurrent code is exactly the drift the registry exists to stop.

``python -m tools.analyze --report thread-model`` (and ``make
thread-model-report`` in CI) emits the model itself -- roles, closures,
the MHP matrix, and per-singleton access evidence (site, via, roles,
lock-set) -- as ``thread_model.json``, the concurrency companion to the
shard-state inventory.  The report exits nonzero if any of the five
concurrency passes (TJA028-TJA032) has unwaived findings.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from tools.analyze import threadmodel
from tools.analyze.findings import ERROR, Finding
from tools.analyze.jit_boundary import is_test_path
from tools.analyze.project import ClassInfo, ProjectContext
from tools.analyze.runner import register_project
from tools.analyze.threadmodel import PKG, ThreadModel

CHECK_ID, CHECK_NAME = "TJA032", "shard-boundary-discipline"
REPORT_VERSION = 1


def _method_may_lock(pc: ProjectContext, tm: ThreadModel, ci: ClassInfo,
                     method: str) -> Optional[bool]:
    """Does ``method`` on (any composite of) ``ci`` transitively acquire
    a resolvable lock?  None when no summary for it exists anywhere (a
    dynamic attribute the model cannot reason about)."""
    found = False
    for k in tm.resolver.composites(ci):
        for c in pc.mro_classes(k):
            s = c.summaries.get(method)
            if s is None:
                continue
            found = True
            for q in tm._closure((s.qual,)):
                rec = tm._summaries.get(q)
                if rec is None:
                    continue
                mod, cls, summary = rec
                for name in summary.acquires:
                    if tm.resolver.lock_id(mod, cls, name) is not None:
                        return True
    return False if found else None


#: Lifecycle methods exempt from the lock_guarded evidence rule: start
#: spawns the coordinating thread (nothing to race yet) and the stop
#: family joins it (the join is itself the synchronization).
_LIFECYCLE = frozenset(("start", "run")) | frozenset(
    threadmodel.STOP_METHOD_NAMES)


def _check_lock_guarded(pc: ProjectContext, tm: ThreadModel, key: str,
                        s) -> List[Finding]:
    out: List[Finding] = []
    if s.kind in threadmodel.BARE_CONTAINER_KINDS:
        for p, ln, via in sorted(s.writes + s.reads):
            if not tm.owner_qual(p, ln):
                continue   # import-time init happens-before any thread
            if threadmodel.locked_by_convention(tm.owner_qual(p, ln)):
                continue
            if not tm.lock_set(p, ln):
                out.append(Finding(
                    CHECK_ID, CHECK_NAME, p, ln, 0, ERROR,
                    f"{key!r} is declared lock_guarded_shared but this "
                    f"access ({via}) holds no lock; take the module lock "
                    "around it or reclassify the singleton"))
        return out
    ci = pc.resolve_class(s.module, s.kind)
    for p, ln, via in sorted(s.writes):
        if not tm.owner_qual(p, ln):
            continue
        if tm.lock_set(p, ln) \
                or threadmodel.locked_by_convention(tm.owner_qual(p, ln)):
            continue
        method = via[:-2] if via.endswith("()") else None
        if method is not None:
            if method in _LIFECYCLE:
                continue
            if ci is not None:
                locks = _method_may_lock(pc, tm, ci, method)
                if locks is True or locks is None:
                    continue
        out.append(Finding(
            CHECK_ID, CHECK_NAME, p, ln, 0, ERROR,
            f"{key!r} is declared lock_guarded_shared but this write "
            f"({via}) neither holds a lock at the site nor goes through "
            f"a lock-acquiring method of {s.kind}; route the mutation "
            "through the guarded API or reclassify"))
    return out


def _mhp_pair(tm: ThreadModel, roles) -> Optional[Tuple[str, str]]:
    ordered = sorted(roles)
    for i, a in enumerate(ordered):
        for b in ordered[i:]:
            if tm.mhp(a, b):
                return a, b
    return None


def _check_shard_local(pc: ProjectContext, tm: ThreadModel, key: str,
                       s) -> List[Finding]:
    ci = pc.resolve_class(s.module, s.kind) \
        if s.kind not in threadmodel.BARE_CONTAINER_KINDS else None
    roles = set()
    unlocked: List[Tuple[str, int, str]] = []
    for p, ln, via in sorted(s.writes):
        rs = tm.roles_at(p, ln)
        roles |= rs
        if not rs or tm.lock_set(p, ln) \
                or threadmodel.locked_by_convention(tm.owner_qual(p, ln)):
            continue
        method = via[:-2] if via.endswith("()") else None
        if method is not None and ci is not None:
            locks = _method_may_lock(pc, tm, ci, method)
            if locks is True or locks is None:
                continue
        unlocked.append((p, ln, via))
    pair = _mhp_pair(tm, roles)
    if pair is None or not unlocked:
        return []
    p, ln, via = unlocked[0]
    a, b = pair
    who = f"role {a} with itself (multi-instance)" if a == b \
        else f"roles {a} and {b}"
    return [Finding(
        CHECK_ID, CHECK_NAME, s.path, s.line, 0, ERROR,
        f"{key!r} is declared shard_local but is mutated from "
        f"may-happen-in-parallel {who} with no lock at e.g. {p}:{ln} "
        f"({via}); within one process the slices already interleave -- "
        "guard it, key it per-thread (waive with the keying argument), "
        "or reclassify")]


def _check_globals(pc: ProjectContext, tm: ThreadModel,
                   reg: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or is_test_path(rel):
            continue
        mod = pc.module_of_path(rel)
        if mod is None:
            continue
        rel_mod = mod.name[len(PKG) + 1:] \
            if mod.name.startswith(PKG + ".") else mod.name
        for g in ctx.by_type(ast.Global):
            roles = sorted(r for r in tm.roles_at(rel, g.lineno)
                           if tm.roles[r].kind == "thread")
            if not roles:
                continue
            for nm in g.names:
                if nm in mod.module_locks:
                    continue
                key = f"{rel_mod}.{nm}"
                if key in reg:
                    continue
                out.append(Finding(
                    CHECK_ID, CHECK_NAME, rel, g.lineno, 0, ERROR,
                    f"`global {nm}` rebind reached from thread role "
                    f"{roles[0]} but {key!r} is not classified in "
                    "SHARD_STATE_REGISTRY: an undeclared process-global "
                    "written from concurrent code; classify it or push "
                    "the state into an owned object"))
    return out


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    from tools.analyze.checks import shard_state
    tm = threadmodel.model(pc)
    inventory, registry, _entry_lines, _reg_line = shard_state.build(pc)
    reg = registry or {}
    findings: List[Finding] = []
    for key, s in sorted(inventory.items()):
        cls = reg.get(key)
        if cls == "lock_guarded_shared":
            findings.extend(_check_lock_guarded(pc, tm, key, s))
        elif cls == "shard_local":
            findings.extend(_check_shard_local(pc, tm, key, s))
    findings.extend(_check_globals(pc, tm, reg))
    findings.sort(key=Finding.sort_key)
    return findings


# -- machine-readable report --------------------------------------------------

def report(pc: ProjectContext) -> Tuple[dict, bool]:
    """The ``--report thread-model`` JSON document and whether the tree
    is clean (no unwaived TJA028-TJA032 findings)."""
    from tools.analyze.checks import (
        check_then_act, shard_state, unguarded_shared_state, wait_discipline,
    )
    from tools.analyze.checks import shutdown_ordering
    tm = threadmodel.model(pc)
    inventory, registry, _el, _rl = shard_state.build(pc)
    reg = registry or {}
    desc = tm.describe()

    singletons = []
    for key, s in sorted(inventory.items()):
        evidence = []
        for write, sites in ((True, s.writes), (False, s.reads)):
            for p, ln, via in sorted(sites):
                evidence.append({
                    "path": p, "line": ln, "via": via, "write": write,
                    "roles": sorted(tm.roles_at(p, ln)),
                    "locks": sorted(tm.lock_set(p, ln)),
                })
        singletons.append({
            "name": key, "kind": s.kind,
            "classification": reg.get(key),
            "evidence": evidence,
        })

    counts: Dict[str, int] = {}
    modules = (unguarded_shared_state, check_then_act, wait_discipline,
               shutdown_ordering)
    for m in modules:
        counts[m.CHECK_ID] = _unwaived(pc, m.check(pc))
    counts[CHECK_ID] = _unwaived(pc, check(pc))

    doc = {
        "version": REPORT_VERSION,
        "generated_by": f"tools.analyze {CHECK_ID} ({CHECK_NAME})",
        "package": PKG,
        "roles": desc["roles"],
        "mhp": desc["mhp"],
        "singletons": singletons,
        "violations": counts,
    }
    ok = not any(counts.values())
    return doc, ok


def _unwaived(pc: ProjectContext, findings: List[Finding]) -> int:
    n = 0
    for f in findings:
        fctx = pc.files.get(f.path)
        if fctx is None or not fctx.waived(f.line, f.check_name):
            n += 1
    return n
