"""TJA004 broad-except: swallowing ``Exception`` must be a decision, not a
default.

In a restart state machine, an ``except Exception: pass`` around a status
write silently corrupts job state -- the job looks Running while its pods are
gone (the failure class ISSUE.md cites from Singularity).  A broad handler is
accepted only when it visibly does one of:

- re-raises (``raise`` anywhere in the handler);
- logs through a recognized logging call (``log.exception(...)``,
  ``logger.warning(...)``, ``logging.error(...)``, ``traceback.*``);
- binds the exception (``as exc``) and actually *uses* the bound name --
  forwarding it to a queue, a result payload, or an error report is
  surfacing, not swallowing; or
- carries an explicit waiver: ``# analyzer: allow[broad-except]: <reason>``
  on the ``except`` line or in the comment block above (the generic waiver
  the runner honors for every pass -- here it is the *documented* escape
  hatch).

Narrow handlers (``except (ConflictError, NotFoundError):``) are never
flagged: catching what you can name is the point.
"""

from __future__ import annotations

import ast
from typing import List

from tools.analyze.findings import FileContext, Finding, WARNING, walk_fast
from tools.analyze.runner import register

LOGGING_METHODS = {"exception", "error", "warning", "critical", "info",
                   "debug", "log"}
BROAD_NAMES = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD_NAMES:
            return True
    return False


def _handler_is_accountable(handler: ast.ExceptHandler) -> bool:
    for node in walk_fast(handler):
        if isinstance(node, ast.Raise):
            return True
        if (handler.name is not None and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return True  # the bound exception is forwarded somewhere
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if fn.attr in LOGGING_METHODS:
                    return True
                root = fn.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id == "traceback":
                    return True
    return False


@register("TJA004", "broad-except")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    findings: List[Finding] = []
    for node in ctx.by_type(ast.ExceptHandler):
        if not _is_broad(node):
            continue
        if _handler_is_accountable(node):
            continue
        what = "bare except" if node.type is None else "except Exception"
        findings.append(Finding(
            "TJA004", "broad-except", ctx.path, node.lineno, node.col_offset,
            WARNING,
            f"{what} neither logs nor re-raises; add logging, narrow the "
            "exception, or waive with "
            "'# analyzer: allow[broad-except]: <reason>'"))
    return findings
