"""TJA021 host-sync-in-hot-loop: device round-trips on the hot path.

A TPU step loop sustains its throughput by keeping the device queue fed
ahead of the host (SURVEY.md §5: the dispatch-ahead pipeline *is* the
goodput).  One ``.item()`` / ``float()`` / ``np.asarray`` / ``argmax`` on
a device value inside the loop drains that pipeline: the host blocks until
the step finishes, the device then idles until the host re-dispatches.
The Gemma-serving comparison (PAPERS.md) measures exactly this class of
stall as the dominant serving overhead after recompiles.

Scope: the ``jit_boundary`` hot-loop map -- loops whose iterations carry
device values, plus every function those loops invoke per tick.  A sync
op is only flagged when its operand is *device-tainted* (produced by or
round-tripped through a dispatching call), so host-side bookkeeping in
the same loop stays quiet.

Deliberate fences stay, waived with a reason -- the canonical ones are
``StepProfiler.step_end``'s ``jax.device_get(sync)`` (the measured
completion barrier; ``block_until_ready`` can return early on the axon
runtime) and the serve tick's per-token ``np.argmax`` (the sampler is
host-side by design; one batched D2H per tick is the documented cost).
``tests/`` are exempt -- asserting on device values is what tests do.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from tools.analyze import jit_boundary as jb
from tools.analyze.findings import Finding, WARNING
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project

#: numpy module aliases whose array-taking calls copy device -> host.
NP_ALIASES = {"np", "numpy", "onp"}
NP_SYNC_ATTRS = {"asarray", "array", "argmax", "argmin"}
SYNC_BUILTINS = {"float", "int", "bool", "complex"}
SYNC_METHODS = {"item", "tolist"}
JAX_FENCES = {"device_get", "block_until_ready"}


def _is_test_path(path: str) -> bool:
    return path.startswith("tests/") or "/tests/" in path


def _tainted_names(taint: Set, node: ast.AST) -> List[str]:
    """Device-tainted value names referenced anywhere under ``node``."""
    hits: List[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in taint:
            hits.append(n.id)
        elif (isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"
                and ("self", n.attr) in taint):
            hits.append(f"self.{n.attr}")
    return sorted(set(hits))


@register_project("TJA021", "host-sync-in-hot-loop")
def check(pc: ProjectContext) -> List[Finding]:
    b = jb.boundary(pc)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, int]] = set()

    def emit(path: str, node: ast.AST, msg: str) -> None:
        key = (path, node.lineno, node.col_offset)
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding("TJA021", "host-sync-in-hot-loop", path,
                                node.lineno, node.col_offset, WARNING, msg))

    def classify(rec: jb.FnRec, cr: jb.CallRec, taint: Set,
                 where: str) -> None:
        ref = cr.ref
        if ref is None:
            return
        call = cr.node
        if ref[0] == "name":
            name = ref[1]
            if name in SYNC_BUILTINS:
                hits = [h for a in call.args
                        for h in _tainted_names(taint, a)]
                if hits:
                    emit(rec.path, call,
                         f"{name}() on device value(s) {hits} {where}; "
                         "each call blocks the host on the device queue "
                         "-- keep the value on-device or read it outside "
                         "the loop")
            elif name in JAX_FENCES:
                hits = [h for a in call.args
                        for h in _tainted_names(taint, a)]
                if hits:
                    emit(rec.path, call,
                         f"{name}() fences on device value(s) {hits} "
                         f"{where}; the dispatch-ahead pipeline drains "
                         "every iteration -- fence once outside, or waive "
                         "with the reason if this is the deliberate "
                         "completion barrier")
        elif ref[0] == "attr":
            leaf, meth = ref[1], ref[2]
            if leaf == "jax" and meth in JAX_FENCES:
                hits = [h for a in call.args
                        for h in _tainted_names(taint, a)]
                if hits:
                    emit(rec.path, call,
                         f"jax.{meth}() on device value(s) {hits} {where}; "
                         "this is a full host sync per iteration -- hoist "
                         "it, or waive with the reason if it is a "
                         "deliberate fence")
            elif leaf in NP_ALIASES and meth in NP_SYNC_ATTRS:
                hits = [h for a in call.args
                        for h in _tainted_names(taint, a)]
                if hits:
                    emit(rec.path, call,
                         f"{leaf}.{meth}() copies device value(s) {hits} "
                         f"to host {where}; use the jnp equivalent "
                         "on-device, or waive if the host-side read is "
                         "the design (e.g. the serve sampler)")
            elif meth in SYNC_METHODS and leaf in taint:
                emit(rec.path, call,
                     f".{meth}() on device value '{leaf}' {where}; one "
                     "blocking device-to-host round-trip per call")
            elif meth == "block_until_ready" and leaf in taint:
                emit(rec.path, call,
                     f"'{leaf}.block_until_ready()' {where}; drains the "
                     "dispatch pipeline every iteration")
        elif ref[0] == "selfattr":
            attr, meth = ref[1], ref[2]
            if meth in SYNC_METHODS | {"block_until_ready"} \
                    and ("self", attr) in taint:
                emit(rec.path, call,
                     f".{meth}() on device value 'self.{attr}' {where}")

    # Ops lexically inside a hot loop.
    for hl in b.hot_loops:
        rec = b.fns.get(hl.fn_qual)
        if rec is None or _is_test_path(rec.path):
            continue
        taint = b.device_taint.get(hl.fn_qual, set())
        loops = [lp for lp in rec.loops if lp.lineno == hl.line]
        for cr in rec.calls:
            if any(lp in cr.loop_stack for lp in loops):
                classify(rec, cr, taint, f"inside the {hl.describe()}")

    # Ops in functions invoked (transitively) once per hot-loop iteration.
    for qual, hl in b.hot_fns.items():
        rec = b.fns.get(qual)
        if rec is None or _is_test_path(rec.path):
            continue
        taint = b.device_taint.get(qual, set())
        if not taint:
            continue
        where = (f"in '{qual.rsplit('.', 1)[-1]}', which runs every "
                 f"iteration of the {hl.describe()}")
        for cr in rec.calls:
            classify(rec, cr, taint, where)

    findings.sort(key=Finding.sort_key)
    return findings
