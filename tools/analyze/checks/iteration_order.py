"""TJA026 iteration-order-hazard: unordered loops with ordered effects.

The event kernel's tie-break is ``(deadline, seq)`` where ``seq`` is the
*arming order* (runtime/events.py); plan expansion appends decision
streams in *loop order* (fleet/chaos.py, fleet/churn.py); seeded RNG
draws consume state in *call order*.  A ``for`` loop over a ``set`` (or
``frozenset``) makes all three hash-randomization-dependent: the loop
body runs in an order that differs between processes, so timers arm in a
different ``seq`` order, streams append in a different element order, and
the same seeded RNG hands different draws to different elements --
byte-identical plans and phase counts for *this* run's PYTHONHASHSEED,
different ones for the next.

Inside ``DETERMINISM_SCOPE`` this pass flags any ``for`` whose iterable
is set-typed (display, ``set()``/``frozenset()`` call, set algebra, a
local or module-level name inferred set-typed, ``list()``/``tuple()``
wrappers included -- materializing doesn't fix the order) *and* whose
body contains an order-dependent effect:

- an append-shaped mutation (``append``/``extend``/``insert``/
  ``appendleft``/``put``/``push``/``heappush``/``publish``/``send``);
- arming/scheduling (``arm``/``schedule``/``fire``/``emit``/``record``);
- a draw from any RNG (a call on an ``rng``-named receiver or a
  ``random.*`` function): draw order is element order;
- a ``yield``: generator output order is element order.

The fix is mechanical -- iterate ``sorted(...)`` -- which is exactly what
the flagged loop's message says.  Membership tests, ``add``/``discard``
into other sets, and dict key deletion are order-independent and pass.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analyze import determinism as det
from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project

CHECK_ID, CHECK_NAME = "TJA026", "iteration-order-hazard"

#: Method leaves whose call inside the loop body is an order-dependent
#: effect (position-encoding mutations and event/timer emission).
ORDER_SENSITIVE = frozenset({
    "append", "extend", "insert", "appendleft", "put", "push", "heappush",
    "publish", "send", "arm", "schedule", "fire", "emit", "record",
})

_RNG_RECEIVER = ("rng", "random", "rand")


def _unordered_iter(mod, rec, df, expr: ast.expr) -> bool:
    """Set-typed after peeling list()/tuple() wrappers; ``sorted(...)``
    (and ``enumerate(sorted(...))`` etc.) is ordered."""
    while (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name)
           and expr.func.id in ("list", "tuple", "iter", "enumerate",
                                "reversed") and expr.args):
        expr = expr.args[0]
    if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name)
            and expr.func.id == "sorted"):
        return False
    return det.is_set_expr(mod, rec, expr, df)


def _effect_in(body: List[ast.stmt]) -> Optional[ast.AST]:
    """First order-dependent effect in the loop body, or None."""
    for stmt in body:
        for node in det.walk_fast(stmt):
            cls = node.__class__
            if cls is ast.Yield or cls is ast.YieldFrom:
                return node
            if cls is not ast.Call:
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr in ORDER_SENSITIVE:
                return node
            recv = fn.value
            leaf = recv.id if isinstance(recv, ast.Name) else (
                recv.attr if isinstance(recv, ast.Attribute) else None)
            if leaf is not None and any(
                    t in leaf.lower() for t in _RNG_RECEIVER):
                return node   # RNG draw: state consumed in element order
    return None


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    df = det.facts(pc)
    findings: List[Finding] = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or not det.in_scope(rel):
            continue
        mod = pc.module_of_path(rel)
        by_fn = {id(rec.node): rec for rec in df.by_path.get(rel, ())}
        parents = ctx.parents
        for loop in ctx.by_type(ast.For):
            rec = None
            anc = parents.get(id(loop))
            while anc is not None:
                rec = by_fn.get(id(anc))
                if rec is not None:
                    break
                anc = parents.get(id(anc))
            if not _unordered_iter(mod, rec, df, loop.iter):
                continue
            effect = _effect_in(loop.body)
            if effect is None:
                continue
            what = ("a yield" if isinstance(effect, (ast.Yield,
                                                     ast.YieldFrom))
                    else f"a {effect.func.attr}() call")
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, rel, loop.lineno, loop.col_offset,
                ERROR,
                "loop iterates a set whose element order is "
                f"hash-randomization-dependent, and its body has {what} "
                f"(line {effect.lineno}) whose effect encodes that order "
                "(appended streams, (deadline, seq) arming order, RNG "
                "draw order); iterate sorted(...) to pin it"))
    findings.sort(key=Finding.sort_key)
    return findings
