"""TJA030 wait-predicate-discipline: every blocking wait is survivable.

Two failure shapes on ``threading`` wait primitives, both invisible to
the lock passes because nothing deadlocks -- the process just stalls:

- **Spurious/missed wakeup.**  ``Condition.wait()`` may return without
  a ``notify`` and *must* return when the predicate became true before
  the waiter got the lock back.  A wait that is not lexically re-checked
  in a loop (``while not pred: cond.wait(...)``) acts on a predicate it
  never verified.  ``Condition.wait_for`` builds the loop in and is
  exempt.  This sub-rule is local and fires anywhere in non-test code.

- **Unbounded park in a stoppable thread.**  ``Event.wait()`` or
  ``Thread.join()`` with no timeout, executed inside a spawned role
  whose owning class has a stop path (``stop``/``shutdown``/...),
  parks that thread forever if the ``set()``/exit it waits for is
  missed -- and ``stop()`` then hangs behind it.  The thread-model
  layer supplies both facts: which role the wait runs in, and whether
  that role's owner is stoppable.  Waits on the main thread (a CLI
  parking on a shutdown event) are deliberate and not flagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analyze import threadmodel
from tools.analyze.findings import ERROR, FileContext, Finding, WARNING
from tools.analyze.jit_boundary import is_test_path
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project
from tools.analyze.threadmodel import ThreadModel

CHECK_ID, CHECK_NAME = "TJA030", "wait-predicate-discipline"


def _in_loop(ctx: FileContext, node: ast.AST) -> bool:
    """Lexically inside a While/For within the enclosing function."""
    anc = ctx.parents.get(id(node))
    while anc is not None:
        if isinstance(anc, (ast.While, ast.For)):
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            return False
        anc = ctx.parents.get(id(anc))
    return False


def _unbounded(call: ast.Call) -> bool:
    """True when the call carries no (non-None) timeout."""
    if call.args:
        return all(isinstance(a, ast.Constant) and a.value is None
                   for a in call.args)
    for kw in call.keywords:
        if kw.arg == "timeout":
            return isinstance(kw.value, ast.Constant) \
                and kw.value.value is None
    return True


def _stoppable_role(tm: ThreadModel, rel: str, line: int) -> Optional[str]:
    """A spawned role containing this site whose owner has a stop path."""
    for rname in sorted(tm.roles_at(rel, line)):
        role = tm.roles[rname]
        if role.kind == "thread" and tm.has_stop_path(role.owner_class):
            return rname
    return None


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    tm = threadmodel.model(pc)
    findings: List[Finding] = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or is_test_path(rel):
            continue
        if ".wait(" not in ctx.source and ".join(" not in ctx.source:
            continue
        for call in ctx.by_type(ast.Call):
            fn = call.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr == "wait":
                kind = tm.condition_kind(rel, call, fn.value)
                if kind == "Condition" and not _in_loop(ctx, call):
                    findings.append(Finding(
                        CHECK_ID, CHECK_NAME, rel, call.lineno, 0, ERROR,
                        "Condition.wait() outside a predicate loop: wakeups "
                        "may be spurious and the predicate may already be "
                        "stale when the lock is re-won; use `while not "
                        "predicate: cond.wait(...)` or cond.wait_for(...)"))
                elif kind == "Event" and _unbounded(call):
                    rname = _stoppable_role(tm, rel, call.lineno)
                    if rname is not None:
                        findings.append(Finding(
                            CHECK_ID, CHECK_NAME, rel, call.lineno, 0,
                            WARNING,
                            f"Event.wait() without a timeout inside thread "
                            f"role {rname} whose owner has a stop path: a "
                            "missed set() parks the thread forever and "
                            "stop() hangs behind it; bound the wait and "
                            "re-check the stop predicate"))
            elif fn.attr == "join" and _unbounded(call):
                rname = _stoppable_role(tm, rel, call.lineno)
                if rname is not None:
                    findings.append(Finding(
                        CHECK_ID, CHECK_NAME, rel, call.lineno, 0, WARNING,
                        f".join() without a timeout inside thread role "
                        f"{rname} whose owner has a stop path: if the "
                        "joined thread never exits, this role -- and the "
                        "stop path waiting on it -- hang; join with a "
                        "timeout and surface the straggler"))
    findings.sort(key=Finding.sort_key)
    return findings
