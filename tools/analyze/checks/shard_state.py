"""TJA027 shard-state-discipline: the module-level mutable-state ledger.

ROADMAP item 3 (horizontal controller scale-out) starts with a question
the code cannot answer about itself at runtime: which module-level
mutable singletons -- ``INCIDENTS``, ``GOODPUT``, ``TELEMETRY``,
``METRICS``, port cursors, sequence counters, transition tables -- are
*shard-local* (each controller shard may own an independent copy),
which are *lock-guarded-shared* (one copy per process, threads
coordinate), and which are *shard-hostile* (their semantics assume a
single global writer over the whole keyspace, so splitting the keyspace
splits the truth).  This pass turns that inventory into a declared,
drift-proof contract, the way TJA007/TJA011/TJA013 do for event
reasons, env vars, and phase transitions:

- every module-level mutable singleton in the package (container
  displays/constructors and project-class constructions --
  ``ModuleInfo.global_mutables``/``global_ctors``; lock objects and
  dunders excluded) must be classified in ``SHARD_STATE_REGISTRY``
  (api/constants.py) as one of ``constant`` / ``shard_local`` /
  ``lock_guarded_shared`` / ``shard_hostile``;
- an unclassified singleton is an **error at its definition** -- new
  global mutable state cannot land without declaring its shard story;
- a registry entry naming no singleton is an **error at the registry**
  (stale inventory; gated on whole-package coverage like TJA011's
  absence claims);
- a witnessed mutation of a ``constant``-classified singleton is an
  **error at the write site** (the classification was a lie);
- ``lock_guarded_shared`` without lock evidence (no lock attribute on
  the singleton's class, no module-level lock beside a bare container)
  is a **warning at the definition**.

``python -m tools.analyze --report shard-state`` emits the full
machine-readable inventory -- every singleton with its classification,
lock evidence, and cross-module read/write sites -- which is the
worklist ROADMAP item 3 consumes (docs/STATIC_ANALYSIS.md documents the
schema).  The report exits nonzero on exactly the error classes above,
which is what ``make shard-state-report`` gates CI on.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.jit_boundary import is_test_path
from tools.analyze.project import ModuleInfo, ProjectContext
from tools.analyze.runner import register_project

CHECK_ID, CHECK_NAME = "TJA027", "shard-state-discipline"

PKG = "trainingjob_operator_tpu"
CONSTANTS_REL = f"{PKG}/api/constants.py"
REGISTRY_NAME = "SHARD_STATE_REGISTRY"
REPORT_VERSION = 1

CLASSIFICATIONS = frozenset({
    "constant", "shard_local", "lock_guarded_shared", "shard_hostile",
})

# Read/write method-name classification now lives in the thread-model
# layer (the canonical copy); this pass and TJA028+ must agree on it.
from tools.analyze.threadmodel import READ_PREFIXES  # noqa: F401  (re-export)
from tools.analyze.threadmodel import is_read_method as _is_read


@dataclass
class Singleton:
    key: str                 # package-relative dotted, "obs.incident.INCIDENTS"
    module: str              # full dotted module
    name: str
    path: str
    line: int
    kind: str                # "dict"/"list"/"set"/"count"/class name
    classification: Optional[str] = None
    lock_guarded: bool = False
    writes: List[Tuple[str, int, str]] = field(default_factory=list)
    reads: List[Tuple[str, int, str]] = field(default_factory=list)


def _registry(mod: ModuleInfo) -> Tuple[Optional[Dict[str, str]],
                                        Dict[str, int], int]:
    """(key -> classification, key -> lineno, registry lineno) from the
    ``SHARD_STATE_REGISTRY`` dict display, resolving value names through
    the module's string constants.  First element is None when the
    registry is not declared at all."""
    if mod.ctx is None or mod.ctx.tree is None:
        return None, {}, 0
    for node in mod.ctx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == REGISTRY_NAME
                and isinstance(node.value, ast.Dict)):
            continue
        entries: Dict[str, str] = {}
        lines: Dict[str, int] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                entries[k.value] = v.value
            elif isinstance(v, ast.Name):
                entries[k.value] = mod.constants.get(v.id, v.id)
            else:
                entries[k.value] = "<non-literal>"
            lines[k.value] = k.lineno
        return entries, lines, node.lineno
    return None, {}, 0


def _inventory(pc: ProjectContext) -> Dict[str, Singleton]:
    """Every module-level mutable singleton in the package, keyed by its
    package-relative dotted name."""
    out: Dict[str, Singleton] = {}
    for mod in pc.modules.values():
        if mod.name != PKG and not mod.name.startswith(PKG + "."):
            continue
        if mod.ctx is None or is_test_path(mod.ctx.path):
            continue
        rel_mod = mod.name[len(PKG) + 1:] if mod.name != PKG else ""
        seen = set()
        for name, (kind, line) in mod.global_mutables.items():
            if name.startswith("__") or name in mod.module_locks:
                continue
            key = f"{rel_mod}.{name}" if rel_mod else name
            out[key] = Singleton(key=key, module=mod.name, name=name,
                                 path=mod.ctx.path, line=line, kind=kind,
                                 lock_guarded=bool(mod.module_locks))
            seen.add(name)
        for name, ctor in mod.global_ctors.items():
            if name in seen or name.startswith("__") \
                    or name in mod.module_locks:
                continue
            ci = pc.resolve_class(mod.name, ctor)
            if ci is None:
                continue   # stdlib/external ctor (getLogger, object(), ...)
            line = 0
            for node in mod.ctx.tree.body:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == name):
                    line = node.lineno
                    break
            key = f"{rel_mod}.{name}" if rel_mod else name
            out[key] = Singleton(
                key=key, module=mod.name, name=name, path=mod.ctx.path,
                line=line, kind=ci.name,
                lock_guarded=bool(ci.lock_attrs) or bool(mod.module_locks))
    return out


def _collect_sites(pc: ProjectContext,
                   inventory: Dict[str, Singleton]) -> None:
    """Attribute every witnessed use of a singleton -- method calls,
    attribute/subscript stores, ``next()`` draws, deletes -- to it, split
    into reads and writes."""
    quals = {f"{s.module}.{s.name}": key for key, s in inventory.items()}
    sing_modules = {s.module for s in inventory.values()}

    for rel, ctx in pc.files.items():
        if ctx.tree is None or is_test_path(rel):
            continue
        mod = pc.module_of_path(rel)
        if mod is None or (mod.name != PKG
                           and not mod.name.startswith(PKG + ".")):
            continue
        local: Dict[str, str] = {}
        mod_alias: Dict[str, str] = {}
        for key, s in inventory.items():
            if s.module == mod.name:
                local[s.name] = key
        for alias, target in mod.imports.items():
            got = quals.get(target)
            if got is not None:
                local[alias] = got
            elif target in sing_modules:
                mod_alias[alias] = target

        def resolve(expr: ast.expr) -> Optional[str]:
            if isinstance(expr, ast.Name):
                return local.get(expr.id)
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name):
                target = mod_alias.get(expr.value.id)
                if target is not None:
                    return quals.get(f"{target}.{expr.attr}")
            return None

        def note(key: str, line: int, via: str, write: bool) -> None:
            s = inventory[key]
            (s.writes if write else s.reads).append((rel, line, via))

        for call in ctx.by_type(ast.Call):
            fn = call.func
            if isinstance(fn, ast.Attribute):
                key = resolve(fn.value)
                if key is not None:
                    note(key, call.lineno, f"{fn.attr}()",
                         not _is_read(fn.attr))
            elif isinstance(fn, ast.Name) and fn.id == "next" and call.args:
                key = resolve(call.args[0])
                if key is not None:
                    note(key, call.lineno, "next()", True)
        for node in ctx.by_type(ast.Assign):
            for t in node.targets:
                key = _store_base(t, resolve)
                if key is not None:
                    note(key, node.lineno, "store", True)
        for node in ctx.by_type(ast.AugAssign):
            key = _store_base(node.target, resolve)
            if key is not None:
                note(key, node.lineno, "augmented store", True)
        for node in ctx.by_type(ast.Delete):
            for t in node.targets:
                key = _store_base(t, resolve)
                if key is not None:
                    note(key, node.lineno, "delete", True)
        for node in ctx.by_type(ast.Subscript):
            if isinstance(node.ctx, ast.Load):
                key = resolve(node.value)
                if key is not None:
                    note(key, node.lineno, "subscript", False)


def _store_base(target: ast.expr, resolve) -> Optional[str]:
    """Singleton behind ``SING[...] = ...`` / ``SING.attr = ...`` /
    ``mod.SING[...] = ...`` store targets."""
    if isinstance(target, (ast.Subscript, ast.Attribute)):
        got = resolve(target.value)
        if got is not None:
            return got
        # one more level: ``incident.INCIDENTS._rings[k] = v``
        inner = target.value
        if isinstance(inner, (ast.Subscript, ast.Attribute)):
            return resolve(inner.value)
    return None


def build(pc: ProjectContext) -> Tuple[Dict[str, Singleton],
                                       Optional[Dict[str, str]],
                                       Dict[str, int], int]:
    """(inventory with sites, registry, registry entry lines, registry
    lineno) -- shared by the pass and the ``--report shard-state`` CLI,
    memoized on the ProjectContext so running both costs one sweep."""
    cached = getattr(pc, "_shard_state", None)
    if cached is not None:
        return cached
    const_mod = pc.ensure_module(CONSTANTS_REL)
    registry, entry_lines, reg_line = (
        _registry(const_mod) if const_mod is not None else (None, {}, 0))
    inventory = _inventory(pc)
    _collect_sites(pc, inventory)
    for key, s in inventory.items():
        if registry:
            s.classification = registry.get(key)
    result = (inventory, registry, entry_lines, reg_line)
    pc._shard_state = result
    return result


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    if pc.ensure_module(CONSTANTS_REL) is None:
        return []   # not this package's tree (bare fixture): nothing to hold
    inventory, registry, entry_lines, reg_line = build(pc)
    findings: List[Finding] = []
    reg = registry or {}

    for key, s in sorted(inventory.items()):
        cls = reg.get(key)
        if cls is None:
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, s.path, s.line, 0, ERROR,
                f"module-level mutable singleton {key!r} ({s.kind}) is not "
                f"classified in {REGISTRY_NAME} (api/constants.py); declare "
                "it constant / shard_local / lock_guarded_shared / "
                "shard_hostile so the scale-out inventory stays complete"))
            continue
        if cls not in CLASSIFICATIONS:
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, CONSTANTS_REL,
                entry_lines.get(key, reg_line), 0, ERROR,
                f"{REGISTRY_NAME}[{key!r}] = {cls!r} is not a valid "
                f"classification ({', '.join(sorted(CLASSIFICATIONS))})"))
            continue
        if cls == "constant":
            for path, line, via in sorted(s.writes):
                findings.append(Finding(
                    CHECK_ID, CHECK_NAME, path, line, 0, ERROR,
                    f"{key!r} is classified constant in {REGISTRY_NAME} "
                    f"but is mutated here ({via}); reclassify it or make "
                    "the mutation a construction-time initialization"))
        elif cls == "lock_guarded_shared" and not s.lock_guarded:
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, s.path, s.line, 0, WARNING,
                f"{key!r} is classified lock_guarded_shared but neither "
                "its class nor its module declares a lock; guard it or "
                "reclassify"))

    # Stale registry entries are an absence claim over the whole package:
    # only report them when the analyzed set actually covers it.
    if registry is not None and pc.covers_package(PKG):
        for key in sorted(set(reg) - set(inventory)):
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, CONSTANTS_REL,
                entry_lines.get(key, reg_line), 0, ERROR,
                f"{REGISTRY_NAME} entry {key!r} matches no module-level "
                "mutable singleton in the package: stale inventory"))

    findings.sort(key=Finding.sort_key)
    return findings


# -- machine-readable report --------------------------------------------------

def report(pc: ProjectContext) -> Tuple[dict, bool]:
    """The ``--report shard-state`` JSON document and whether it is clean
    (classified, not stale, constants unmutated)."""
    inventory, registry, _entry_lines, _reg_line = build(pc)
    reg = registry or {}
    singletons = []
    unclassified: List[str] = []
    violations: List[dict] = []
    for key, s in sorted(inventory.items()):
        cls = reg.get(key)
        if cls is None or cls not in CLASSIFICATIONS:
            unclassified.append(key)
        elif cls == "constant" and s.writes:
            violations.extend({
                "singleton": key, "path": p, "line": ln, "via": via,
            } for p, ln, via in sorted(s.writes))
        singletons.append({
            "name": key,
            "path": s.path,
            "line": s.line,
            "kind": s.kind,
            "classification": cls if cls in CLASSIFICATIONS else None,
            "lock_guarded": s.lock_guarded,
            "writes": [{"path": p, "line": ln, "via": via}
                       for p, ln, via in sorted(s.writes)],
            "reads": [{"path": p, "line": ln, "via": via}
                      for p, ln, via in sorted(s.reads)],
            "modules": sorted({p for p, _ln, _via in s.writes + s.reads}),
        })
    stale = sorted(set(reg) - set(inventory)) \
        if registry is not None and pc.covers_package(PKG) else []
    doc = {
        "version": REPORT_VERSION,
        "generated_by": f"tools.analyze {CHECK_ID} ({CHECK_NAME})",
        "package": PKG,
        "registry_declared": registry is not None,
        "singletons": singletons,
        "unclassified": unclassified,
        "stale": stale,
        "constant_violations": violations,
    }
    ok = not unclassified and not stale and not violations \
        and registry is not None
    return doc, ok
