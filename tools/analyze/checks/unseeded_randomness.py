"""TJA024 unseeded-randomness: seeded-RNG discipline in determinism scope.

The chaos/churn planes promise "same (profile, seed) => byte-identical
plan" (fleet/chaos.py, fleet/churn.py) and the event kernel promises
"same seed => same phase counts" (runtime/sim.py, runtime/events.py).
Both hold only while every random draw flows through an explicitly seeded
``random.Random(seed)`` threaded from the profile.  One module-level
``random.*`` call -- whose hidden global state any import or test may
perturb -- or one ``uuid4()``/``os.urandom`` read breaks the contract for
*some* seed without failing the smokes' seeds.

Inside ``DETERMINISM_SCOPE`` this pass makes every such construct an
error at the call site:

- module-level ``random.*`` draws and state pokes (``random.seed`` too:
  reseeding the global generator is how the perturbation happens);
- ``random.Random()`` with no arguments and ``random.SystemRandom`` (both
  seed from the OS);
- legacy ``numpy.random`` globals (``np.random.rand`` ...); seeded
  ``default_rng(seed)`` is allowed;
- ``uuid.uuid1``/``uuid.uuid4``, ``os.urandom``, ``secrets.*``;
- builtin ``hash()`` -- str/bytes hashes are randomized per process
  (PYTHONHASHSEED), so any hash-derived decision is run-dependent.

Scope resolution is interprocedural only in the sense that the scope is
*path*-based; the value-flow version of this contract (a nondeterministic
value reaching a digest anywhere in the package) is TJA025's job.
"""

from __future__ import annotations

import ast
from typing import List

from tools.analyze import determinism as det
from tools.analyze.findings import ERROR, Finding
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project

CHECK_ID, CHECK_NAME = "TJA024", "unseeded-randomness"


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    findings: List[Finding] = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or not det.in_scope(rel):
            continue
        mod = pc.module_of_path(rel)
        for call in ctx.by_type(ast.Call):
            msg = _violation(mod, call)
            if msg is not None:
                findings.append(Finding(
                    CHECK_ID, CHECK_NAME, rel, call.lineno,
                    call.col_offset, ERROR, msg))
    findings.sort(key=Finding.sort_key)
    return findings


def _violation(mod, call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "hash":
        if mod is not None and (fn.id in mod.imports
                                or fn.id in mod.functions):
            return None
        return ("builtin hash() in determinism scope: str/bytes hashes are "
                "randomized per process (PYTHONHASHSEED), so any decision "
                "derived from one is run-dependent; key on the value itself "
                "or a stable digest")
    canon = det.canonical_callee(mod, fn)
    if canon is None:
        return None
    if canon in det.GLOBAL_RANDOM:
        return (f"module-level {canon}() in determinism scope: the global "
                "generator's state is shared with every other import, so "
                "the draw sequence is not a function of the profile seed; "
                "draw from an explicitly seeded random.Random threaded "
                "from the profile/plan")
    if canon == "random.Random" and not call.args:
        return ("random.Random() without a seed in determinism scope "
                "seeds from the OS; construct it as random.Random(seed) "
                "with the profile/plan seed")
    if canon == "random.SystemRandom":
        return ("random.SystemRandom draws OS entropy and cannot be "
                "seeded; determinism scope requires random.Random(seed)")
    if canon.startswith("numpy.random.") and not (
            canon == "numpy.random.default_rng" and call.args):
        return (f"legacy numpy global RNG ({canon}) in determinism scope; "
                "use numpy.random.default_rng(seed) and thread the "
                "generator explicitly")
    if canon in ("uuid.uuid1", "uuid.uuid4", "os.urandom") \
            or canon.startswith("secrets."):
        return (f"{canon}() is unseedable OS entropy; determinism scope "
                "must derive identifiers from the seeded RNG or from "
                "deterministic inputs (names, counters)")
    return None
