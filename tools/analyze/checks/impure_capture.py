"""TJA023 impure-capture: side effects inside the traced region.

A function staged out by jit runs its *Python* body once, at trace time;
only the jaxpr runs per step.  Code inside the traced-region closure that
mutates state outliving the trace is therefore a silent semantic bug:

- appending to / updating a module-global or closed-over container
  records ONE entry ever, not one per step;
- ``global`` / ``nonlocal`` writes fire once at trace time;
- ``self.attr = ...`` in a traced method mutates the object during
  tracing, then never again;
- ``print`` / ``logging`` emit a tracer repr once, which reads like a
  per-step log but is not (``jax.debug.print`` is the staged form).

TJA006 catches the print/host-sync shapes per file for functions visibly
wrapped in the same module; this pass extends the same discipline to the
whole interprocedural closure from ``jit_boundary`` -- helpers two modules
away from the ``jax.jit`` call included.

Trace-local mutation stays allowed: building a Python list of per-layer
outputs inside the traced entry (the unrolled-loop idiom) is fine, so a
mutator is only flagged when its receiver resolves *outside* the traced
region -- to module scope or to a lexical parent that is not itself part
of the closure (e.g. ``__init__`` locals captured by a jitted lambda).
``tests/`` are exempt.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analyze import jit_boundary as jb
from tools.analyze.findings import ERROR, Finding, WARNING
from tools.analyze.project import ProjectContext
from tools.analyze.runner import register_project

MUTATORS = {"append", "extend", "add", "insert", "update", "setdefault",
            "pop", "popleft", "appendleft", "remove", "clear", "write"}
LOG_RECEIVERS = {"logging", "logger", "log", "LOG"}
LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}

_SCOPE_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_test_path(path: str) -> bool:
    return path.startswith("tests/") or "/tests/" in path


def _owner_scope(b: jb.Boundary, rec: jb.FnRec,
                 name: str) -> Optional[jb.FnRec]:
    """The lexical scope that binds ``name``, walking outwards; the module
    scope (``*.<module>``) when it is a module-level binding."""
    scope = rec
    while scope is not None:
        if name in scope.local_names:
            return scope
        scope = b.fns.get(scope.parent) if scope.parent else None
    modscope = b.fns.get(f"{rec.module}.<module>")
    if modscope is not None and name in modscope.local_names:
        return modscope
    return None


def _body_stmts(rec: jb.FnRec) -> List[ast.stmt]:
    node = rec.node
    if isinstance(node, ast.Lambda):
        return []
    return list(node.body)


def _own_nodes(rec: jb.FnRec):
    """Walk this scope's statements without descending into nested defs
    (they are separate closure members and report for themselves)."""
    stack: List[ast.AST] = list(_body_stmts(rec))
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _SCOPE_TYPES):
                stack.append(child)


@register_project("TJA023", "impure-capture")
def check(pc: ProjectContext) -> List[Finding]:
    b = jb.boundary(pc)
    findings: List[Finding] = []

    def emit(path: str, node: ast.AST, sev: str, msg: str) -> None:
        findings.append(Finding("TJA023", "impure-capture", path,
                                node.lineno, node.col_offset, sev, msg))

    for qual, sites in sorted(b.closure.items()):
        rec = b.fns.get(qual)
        if rec is None or _is_test_path(rec.path):
            continue
        via = sites[0].describe() if sites else "a traced region"
        short = qual.rsplit(".", 1)[-1]

        # Calls recorded by the scope walker: mutators, print, logging.
        for cr in rec.calls:
            ref = cr.ref
            if ref is None:
                continue
            if ref[0] == "name" and ref[1] == "print":
                emit(rec.path, cr.node, WARNING,
                     f"print() inside '{short}', traced from the {via}; "
                     "it runs once at trace time, not per step -- use "
                     "jax.debug.print")
            elif (ref[0] == "attr" and ref[1] in LOG_RECEIVERS
                    and ref[2] in LOG_METHODS):
                emit(rec.path, cr.node, WARNING,
                     f"{ref[1]}.{ref[2]}() inside '{short}', traced from "
                     f"the {via}; it logs a tracer repr once at trace "
                     "time -- log outside the traced region or use "
                     "jax.debug.print")
            elif ref[0] == "attr" and ref[2] in MUTATORS:
                # A mutator whose result is bound is a functional API that
                # happens to share the name (optax's ``tx.update(...)``
                # returns new state); only a discarded result is the
                # in-place shape.
                if cr.targets:
                    continue
                leaf = ref[1]
                owner = _owner_scope(b, rec, leaf)
                if owner is None:
                    continue        # unknown receiver: stay quiet
                if owner.qual in b.closure:
                    continue        # trace-local container: allowed
                kind = ("module-level state"
                        if owner.qual.endswith(".<module>")
                        else f"state captured from '{owner.qual}'")
                emit(rec.path, cr.node, ERROR,
                     f"'{leaf}.{ref[2]}()' inside '{short}' mutates "
                     f"{kind} at trace time (traced from the {via}); the "
                     "mutation happens once, not per step -- thread the "
                     "value through the computation instead")
            elif ref[0] == "selfattr" and ref[2] in MUTATORS \
                    and not cr.targets:
                emit(rec.path, cr.node, ERROR,
                     f"'self.{ref[1]}.{ref[2]}()' inside traced method "
                     f"'{short}' (from the {via}) mutates object state at "
                     "trace time; it will not happen per step")

        # Statement-level writes: global/nonlocal and self.attr targets.
        for node in _own_nodes(rec):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for n in ast.walk(t):
                        if (isinstance(n, ast.Name)
                                and n.id in rec.outer_decls):
                            emit(rec.path, node, ERROR,
                                 f"write to global/nonlocal '{n.id}' "
                                 f"inside '{short}', traced from the "
                                 f"{via}; it executes once at trace "
                                 "time -- return the value instead")
                        elif (isinstance(n, ast.Attribute)
                                and isinstance(n.value, ast.Name)
                                and n.value.id == "self"
                                and isinstance(n.ctx, ast.Store)):
                            emit(rec.path, node, WARNING,
                                 f"'self.{n.attr} = ...' inside traced "
                                 f"method '{short}' (from the {via}); "
                                 "object state mutates at trace time "
                                 "only -- return the new value")

    findings.sort(key=Finding.sort_key)
    return findings
