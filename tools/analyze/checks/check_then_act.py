"""TJA029 check-then-act: racy test-then-mutate on MHP-shared state.

The classic lost-update shape::

    if key not in pending:        # thread A and thread B both pass
        pending[key] = make()     # one of the two writes is silently lost

is invisible to the lock passes when *neither* statement takes a lock,
and invisible to TJA028 when every individual access is a GIL-atomic
single op -- the race is the *gap between* the test and the act.  This
pass flags an ``if`` whose test reads an MHP-shared object (a
module-global bare container or a shared instance container attribute,
sharedness established by the thread-model layer) and whose body
mutates the same object, when **no lock region lexically spans the
whole conditional** -- a lock around only the mutation does not close
the gap, and correctly-locked code (``with lock: if k not in d: ...``)
has a non-empty lock-set at the ``if`` and is skipped.

Only conditionals inside a thread role's closure fire: module-level
init code and unreached helpers prove nothing.  Benign last-writer-wins
patterns (idempotent cache fills where both computed values are
equivalent) carry waivers with that reasoning.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze import threadmodel
from tools.analyze.findings import ERROR, Finding
from tools.analyze.jit_boundary import is_test_path
from tools.analyze.project import ClassInfo, ProjectContext, _self_attr
from tools.analyze.runner import register_project
from tools.analyze.threadmodel import ThreadModel, is_read_method

CHECK_ID, CHECK_NAME = "TJA029", "check-then-act"

#: Object tags: ("g", singleton key) | ("a", class qual, attr name).
Obj = Tuple


def _mhp_capable(tm: ThreadModel, roles: Set[str]) -> bool:
    ordered = sorted(roles)
    for i, a in enumerate(ordered):
        for b in ordered[i:]:
            if tm.mhp(a, b):
                return True
    return False


def _shared_globals(pc: ProjectContext,
                    tm: ThreadModel) -> Dict[Tuple[str, str], str]:
    """(module, name) -> singleton key for bare-container globals whose
    witnessed accesses span MHP-capable roles."""
    from tools.analyze.checks import shard_state
    inventory, _reg, _lines, _rl = shard_state.build(pc)
    out: Dict[Tuple[str, str], str] = {}
    for key, s in inventory.items():
        if s.kind not in threadmodel.BARE_CONTAINER_KINDS:
            continue
        roles: Set[str] = set()
        for p, ln, _via in s.writes + s.reads:
            roles |= tm.roles_at(p, ln)
        if _mhp_capable(tm, roles):
            out[(s.module, s.name)] = key
    return out


def _shared_attrs(tm: ThreadModel) -> Set[Tuple[str, str]]:
    out: Set[Tuple[str, str]] = set()
    for (cls_qual, attr), accesses in tm.attr_accesses().items():
        roles: Set[str] = set()
        for a in accesses:
            roles |= tm.roles_of(a.qual)
        if _mhp_capable(tm, roles):
            out.add((cls_qual, attr))
    return out


@register_project(CHECK_ID, CHECK_NAME)
def check(pc: ProjectContext) -> List[Finding]:
    tm = threadmodel.model(pc)
    if not any(r.kind == "thread" for r in tm.roles.values()):
        return []
    shared_globals = _shared_globals(pc, tm)
    shared_attrs = _shared_attrs(tm)
    if not shared_globals and not shared_attrs:
        return []
    findings: List[Finding] = []

    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None or is_test_path(rel):
            continue
        mod = pc.module_of_path(rel)
        if mod is None:
            continue
        # Names in this module resolving to a shared global.
        local: Dict[str, str] = {}
        for (m, n), key in shared_globals.items():
            if m == mod.name:
                local[n] = key
        for alias, target in mod.imports.items():
            m, _, n = target.rpartition(".")
            key = shared_globals.get((m, n))
            if key is not None:
                local[alias] = key
        if not local and not shared_attrs:
            continue
        by_node = {id(ci.node): ci for ci in mod.classes.values()}
        parents = ctx.parents

        for if_node in ctx.by_type(ast.If):
            if not tm.roles_at(rel, if_node.lineno):
                continue   # not witnessed to run on any thread role
            owner: Optional[ClassInfo] = None
            anc = parents.get(id(if_node))
            while anc is not None:
                if isinstance(anc, ast.ClassDef):
                    owner = by_node.get(id(anc))
                    break
                anc = parents.get(id(anc))

            def obj_of(expr: ast.expr) -> Optional[Obj]:
                if isinstance(expr, ast.Name):
                    key = local.get(expr.id)
                    return ("g", key) if key is not None else None
                attr = _self_attr(expr)
                if attr is not None and owner is not None:
                    defining = tm._defining_class(owner, attr)
                    if defining is not None \
                            and (defining, attr) in shared_attrs:
                        return ("a", defining, attr)
                return None

            tested: Set[Obj] = set()
            for n in ast.walk(if_node.test):
                obj = obj_of(n)
                if obj is not None:
                    tested.add(obj)
            if not tested:
                continue
            if tm.lock_set(rel, if_node.lineno):
                continue   # a lock region spans both the test and the act
            mutated = _mutation_of(if_node.body, tested, obj_of)
            if mutated is None:
                continue
            obj, via, line = mutated
            what = (f"module-global {obj[1]!r}" if obj[0] == "g"
                    else f"instance attribute {obj[1]}.{obj[2]}")
            findings.append(Finding(
                CHECK_ID, CHECK_NAME, rel, if_node.lineno, 0, ERROR,
                f"check-then-act race on {what}: the test here and the "
                f"mutation at line {line} ({via}) are not spanned by a "
                "common lock, so two threads can both pass the test and "
                "double-apply the act; hold one lock across the whole "
                "conditional"))
    findings.sort(key=Finding.sort_key)
    return findings


def _mutation_of(stmts: List[ast.stmt], tested: Set[Obj],
                 obj_of) -> Optional[Tuple[Obj, str, int]]:
    """First mutation of a tested object inside ``stmts``."""
    for stmt in stmts:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                fn = n.func
                if isinstance(fn, ast.Attribute) \
                        and not is_read_method(fn.attr):
                    obj = obj_of(fn.value)
                    if obj in tested:
                        return obj, f"{fn.attr}()", n.lineno
                elif isinstance(fn, ast.Name) and fn.id == "next" and n.args:
                    obj = obj_of(n.args[0])
                    if obj in tested:
                        return obj, "next()", n.lineno
            elif isinstance(n, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = n.targets if isinstance(n, (ast.Assign, ast.Delete))\
                    else [n.target]
                for t in targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        obj = obj_of(t) if _self_attr(t) is not None \
                            else obj_of(t.value)
                        if obj in tested:
                            return obj, "store", n.lineno
    return None
