"""TJA017 exception-escape: thread targets that can die silently.

A ``threading.Thread`` target that lets an exception propagate doesn't crash
the process -- the thread prints a traceback (or not, under a redirected
stderr) and *vanishes*, while everything that depended on it waits forever:
the pserver's ``handle`` thread dying on one malformed frame leaves the
worker blocked in ``recv`` for the rest of the job; a controller worker loop
dying strands every job hashed to it.  The reference operator's restart
machine exists precisely because silent partial death is the worst failure
mode.

The pass computes, per function, the set of exception type names that can
*escape* it:

- explicit ``raise TypeName(...)`` sites and ``assert`` statements in the
  function's own body (nested defs excluded -- deferred contexts);
- transitively, escapes of resolvable callees: nested functions by lexical
  name, module functions (directly or via imports), ``self.`` methods
  through the project MRO;
- minus whatever enclosing ``try``/``except`` clauses catch *at that site*
  (lexical nesting gives exact handler scoping: handlers guard only the
  ``try`` body, not their own bodies or the ``else``).

A whole-project fixpoint closes recursion.  Findings fire only for **thread
entry points** -- functions passed as ``Thread(target=...)`` (or ``run``
methods of ``Thread`` subclasses) -- anchored at the spawn site.  Unresolved
callees contribute nothing: this pass reports witnesses, not absence proofs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, Finding, _LOCAL_BARRIERS
from tools.analyze.project import ProjectContext, _self_attr
from tools.analyze.runner import register_project
from tools.analyze.checks._flow import (
    call_dotted, enclosing, functions_of, parents_of,
)
from tools.analyze.cfg import handler_type_names

#: Deliberate process/thread teardown channels, never "silent death".
EXEMPT = {"SystemExit", "KeyboardInterrupt", "GeneratorExit", "StopIteration"}


def _raise_types(stmt: ast.Raise, parents) -> Set[str]:
    exc = stmt.exc
    if exc is None:
        # bare re-raise: escapes whatever the enclosing handler caught.
        h = enclosing(parents, stmt, ast.ExceptHandler)
        return set(handler_type_names(h)) if h is not None else {"*"}
    if isinstance(exc, ast.Call):
        exc = exc.func
    if isinstance(exc, ast.Attribute):
        return {exc.attr}
    if isinstance(exc, ast.Name):
        # ``raise ValueError`` (class) vs ``raise err`` (instance var):
        # CamelCase names are types, lowercase ones are opaque re-raises.
        return {exc.id} if exc.id[:1].isupper() else {"*"}
    return {"*"}


def _caught_at(site: ast.AST, fn: ast.AST, parents) -> Tuple[Set[str], bool]:
    """(caught type names, catches_everything) from the ``try`` statements
    whose *body* lexically contains ``site``, walking out to ``fn``."""
    caught: Set[str] = set()
    cur = site
    node = parents.get(id(cur))
    while node is not None and cur is not fn:
        if isinstance(node, ast.Try) and any(b is cur for b in node.body):
            for h in node.handlers:
                names = set(handler_type_names(h))
                caught |= names
                if names & {"*", "BaseException", "Exception"}:
                    return caught, True
        cur, node = node, parents.get(id(node))
    return caught, False


class _Escapes:
    """Per-function escape sets with a project-wide fixpoint."""

    def __init__(self, pc: ProjectContext):
        self.pc = pc
        self.sets: Dict[int, Set[str]] = {}
        self.sites: List[Tuple[ast.AST, dict, Optional[str],
                               Optional[str]]] = []
        # (fn node, parents map, module name, class name) per function.
        self.by_name: Dict[Tuple[str, str], ast.AST] = {}
        self._resolved: Dict[int, List[ast.AST]] = {}
        self._caught: Dict[int, Tuple[Set[str], bool]] = {}
        #: id(fn) -> {name: nested def node} directly inside fn's body.
        self._local_defs: Dict[int, Dict[str, ast.AST]] = {}
        #: id(fn) -> the Call/Raise/Assert nodes in fn's own body.
        self._interesting: Dict[int, List[ast.AST]] = {}

    @staticmethod
    def _owner(parents, node) -> Optional[ast.AST]:
        """Nearest enclosing scope barrier -- the function (or class/lambda)
        whose ``walk_local`` would yield ``node``."""
        cur = parents.get(id(node))
        while cur is not None and cur.__class__ not in _LOCAL_BARRIERS:
            cur = parents.get(id(cur))
        return cur

    def index(self) -> None:
        if self.sites:
            return  # already indexed (check() indexes before solving)
        for rel, ctx in self.pc.files.items():
            if ctx.tree is None:
                continue
            mod = self.pc.module_of_path(rel)
            parents = parents_of(ctx)
            for fn in functions_of(ctx):
                cls = enclosing(parents, fn, ast.ClassDef)
                self.sites.append((fn, parents, mod.name if mod else None,
                                   cls.name if cls else None))
                self.sets[id(fn)] = set()
                self._local_defs[id(fn)] = {}
            # Attribute nested defs and raise/assert/call sites to their
            # owning function by parent-chain (#interesting-nodes x depth)
            # instead of re-walking every function body (#all-nodes): the
            # body rewalks were this pass's largest slice of the lint
            # budget.  Owner == nearest barrier reproduces walk_local's
            # membership exactly; order within a set is irrelevant to the
            # fixpoint.
            for d in functions_of(ctx):
                own = self._owner(parents, d)
                if own is not None:
                    defs = self._local_defs.get(id(own))
                    if defs is not None:
                        defs[d.name] = d
            for node in ctx.by_type(ast.Call, ast.Raise, ast.Assert):
                own = self._owner(parents, node)
                if own is not None and id(own) in self.sets:
                    self._interesting.setdefault(id(own), []).append(node)

    def _callee_nodes(self, call: ast.Call, fn: ast.AST, parents,
                      mod_name: Optional[str],
                      cls_name: Optional[str]) -> List[ast.AST]:
        out: List[ast.AST] = []
        f = call.func
        mod = self.pc.modules.get(mod_name) if mod_name else None
        if isinstance(f, ast.Name):
            # lexically visible nested def, walking enclosing functions out.
            scope = fn
            while scope is not None:
                hit = self._local_defs.get(id(scope), {}).get(f.id)
                if hit is not None:
                    return [hit]
                scope = enclosing(parents, scope, ast.FunctionDef,
                                  ast.AsyncFunctionDef)
            if mod is not None:
                if f.id in mod.functions:
                    return [mod.functions[f.id]]
                target = mod.imports.get(f.id)
                if target:
                    tmod, _, leaf = target.rpartition(".")
                    mi = self.pc.modules.get(tmod)
                    if mi is not None and leaf in mi.functions:
                        return [mi.functions[leaf]]
        elif isinstance(f, ast.Attribute):
            attr = _self_attr(f.value)
            if isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and mod is not None and cls_name in (mod.classes or {}):
                ci = mod.classes[cls_name]
                hit = self.pc.mro_methods(ci).get(f.attr)
                if hit is not None:
                    return [hit[1]]
            dotted = call_dotted(call)
            if dotted and mod is not None:
                head, _, leaf = dotted.rpartition(".")
                mi = self.pc.modules.get(mod.imports.get(head, head))
                if mi is not None and leaf in mi.functions:
                    return [mi.functions[leaf]]
        return out

    def solve(self, entry_ids: Optional[Set[int]] = None) -> None:
        """Precompute, per function, the constant escapes (own raises and
        asserts, handler-filtered) and the call dependencies (callee fn id
        + caught filter at the site); the fixpoint then iterates only that
        structure -- no re-walking per round.

        Findings only ever read the escape sets of thread *entry points*,
        and a function's set depends only on its (transitive) callees -- so
        with ``entry_ids`` the prep and fixpoint run over just the call
        closure of those functions.  On this tree that is a few hundred of
        several thousand defs; the rest never influence a finding."""
        self.index()
        site_of = {id(fn): (fn, parents, mod_name, cls_name)
                   for fn, parents, mod_name, cls_name in self.sites}
        const: Dict[int, Set[str]] = {}
        deps: Dict[int, List[Tuple[int, Set[str]]]] = {}

        def prep(fid: int) -> None:
            fn, parents, mod_name, cls_name = site_of[fid]
            const[fid] = set()
            deps[fid] = []
            for node in self._interesting.get(fid, ()):
                ncls = node.__class__
                if ncls is ast.Call:
                    callees = self._callee_nodes(node, fn, parents,
                                                 mod_name, cls_name)
                    if not callees:
                        continue
                    types: Set[str] = set()
                elif ncls is ast.Raise:
                    types = _raise_types(node, parents)
                    callees = []
                    if not types:
                        continue
                elif ncls is ast.Assert:
                    types = {"AssertionError"}
                    callees = []
                else:
                    continue
                caught, all_caught = _caught_at(node, fn, parents)
                if all_caught:
                    continue
                const[fid] |= {t for t in types
                               if t not in caught and t not in EXEMPT}
                for callee in callees:
                    deps[fid].append((id(callee), caught))

        if entry_ids is None:
            work = set(site_of)
            for fid in work:
                prep(fid)
        else:
            work = set()
            stack = [fid for fid in entry_ids if fid in site_of]
            while stack:
                fid = stack.pop()
                if fid in work:
                    continue
                work.add(fid)
                prep(fid)
                stack.extend(cid for cid, _ in deps[fid]
                             if cid in site_of and cid not in work)
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fid in work:
                acc = self.sets[fid]
                before = len(acc)
                acc |= const.get(fid, set())
                for callee_id, caught in deps.get(fid, ()):
                    acc |= {t for t in self.sets.get(callee_id, set())
                            if t not in caught and t not in EXEMPT}
                if len(acc) != before:
                    changed = True


def _target_functions(pc: ProjectContext, esc: _Escapes
                      ) -> List[Tuple[str, int, str, ast.AST]]:
    """(path, spawn line, printable name, fn node) per thread entry point."""
    out = []
    for rel, ctx in sorted(pc.files.items()):
        if ctx.tree is None:
            continue
        mod = pc.module_of_path(rel)
        parents = parents_of(ctx)
        for call in ctx.by_type(ast.Call):
            f = call.func
            # Cheap name gate before building the dotted string: almost no
            # call in the tree is a Thread construction.
            if not (f.__class__ is ast.Name and f.id == "Thread"
                    or f.__class__ is ast.Attribute and f.attr == "Thread"):
                continue
            dotted = call_dotted(call)
            if dotted not in ("threading.Thread", "Thread"):
                continue
            tgt = next((kw.value for kw in call.keywords
                        if kw.arg == "target"), None)
            if tgt is None:
                continue
            node: Optional[ast.AST] = None
            label = ast.unparse(tgt) if hasattr(ast, "unparse") else "target"
            if isinstance(tgt, ast.Name):
                fn = enclosing(parents, call, ast.FunctionDef,
                               ast.AsyncFunctionDef)
                hits = esc._callee_nodes(
                    ast.Call(func=tgt, args=[], keywords=[]), fn or ctx.tree,
                    parents, mod.name if mod else None, None)
                node = hits[0] if hits else None
                if node is None and mod is not None \
                        and tgt.id in mod.functions:
                    node = mod.functions[tgt.id]
            elif isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" and mod is not None:
                cls = enclosing(parents, call, ast.ClassDef)
                if cls is not None and cls.name in mod.classes:
                    hit = pc.mro_methods(mod.classes[cls.name]).get(tgt.attr)
                    node = hit[1] if hit is not None else None
            if node is not None:
                out.append((rel, call.lineno, label, node))
    return out


@register_project("TJA017", "exception-escape")
def check(pc: ProjectContext) -> List[Finding]:
    esc = _Escapes(pc)
    esc.index()
    targets = _target_functions(pc, esc)
    esc.solve(entry_ids={id(fn) for _, _, _, fn in targets})
    findings: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for rel, line, label, fn in targets:
        types = sorted(esc.sets.get(id(fn), set()) - EXEMPT)
        if not types or (rel, line) in seen:
            continue
        seen.add((rel, line))
        findings.append(Finding(
            "TJA017", "exception-escape", rel, line, 0, ERROR,
            f"thread target {label} can die silently: "
            f"{', '.join(types)} escape(s) uncaught -- wrap the loop body "
            f"in try/except and log (a dead thread hangs its peers)"))
    findings.sort(key=Finding.sort_key)
    return findings
