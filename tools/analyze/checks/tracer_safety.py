"""TJA006 tracer-safety: traced values are not Python values.

Inside a function staged out by ``jit``/``pmap``/``shard_map`` (Podracer,
arxiv 2104.06272: the whole TPU program is one traced computation), the
arguments are tracers.  Three bug classes:

- ``if x > 0:`` / ``while err > tol:`` on a traced value raises a
  ``ConcretizationTypeError`` at trace time *if you're lucky* -- or, when the
  value happens to be concrete during tracing (weak types, consts), silently
  bakes one branch into the compiled program;
- ``float(x)`` / ``int(x)`` / ``x.item()`` / ``x.tolist()`` force a host
  sync, a device round-trip per call inside the hot step function;
- ``print(...)`` runs at *trace* time, once, not per step -- use
  ``jax.debug.print``.

Scope: ``models/``, ``ops/``, ``parallel/``.  A function counts as traced
when decorated with ``jit``/``pmap`` (bare, ``jax.``-qualified, or under
``partial(...)``) or when its name is passed to ``jax.jit(...)`` /
``pmap(...)`` / ``shard_map(...)`` in the same file.  Parameters named in
``static_argnames``/``static_argnums`` are exempt, as are ``x is None``
checks (concrete at trace time).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.analyze.findings import (
    ERROR, FileContext, Finding, WARNING, walk_fast,
)
from tools.analyze.runner import register

SCOPE_DIRS = ("/models/", "/ops/", "/parallel/")
TRACING_WRAPPERS = {"jit", "pmap", "shard_map"}
HOST_SYNC_METHODS = {"item", "tolist"}
HOST_SYNC_BUILTINS = {"float", "int", "bool", "complex"}


def _base_name(node: ast.expr) -> Optional[str]:
    """'jit' for ``jit``, ``jax.jit``, ``jax.experimental.shard_map``..."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _tracing_call(call: ast.Call) -> Optional[ast.Call]:
    """The jit/pmap/shard_map Call when ``call`` is one (possibly inside
    partial(...)), else None."""
    name = _base_name(call.func)
    if name in TRACING_WRAPPERS:
        return call
    if name == "partial" and call.args:
        inner = call.args[0]
        if _base_name(inner) in TRACING_WRAPPERS:
            return call  # statics live on the partial call itself
    return None


def _static_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        parts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for p in parts:
            if isinstance(p, ast.Constant) and isinstance(p.value, str):
                out.add(p.value)
    return out


def _static_nums(call: ast.Call) -> Set[int]:
    out: Set[int] = set()
    for kw in call.keywords:
        if kw.arg != "static_argnums":
            continue
        v = kw.value
        parts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for p in parts:
            if isinstance(p, ast.Constant) and isinstance(p.value, int):
                out.add(p.value)
    return out


def _traced_functions(nodes: list) -> Dict[str, ast.Call]:
    """function name -> the tracing Call that wraps it (for statics)."""
    wrapped: Dict[str, ast.Call] = {}
    funcs: Dict[str, ast.FunctionDef] = {}
    for node in nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[node.name] = node
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    tc = _tracing_call(dec)
                    if tc is not None:
                        wrapped[node.name] = tc
                elif _base_name(dec) in TRACING_WRAPPERS:
                    wrapped[node.name] = ast.Call(func=dec, args=[],
                                                  keywords=[])
        elif isinstance(node, ast.Call):
            tc = _tracing_call(node)
            # jax.jit(fn, ...) / shard_map(fn, mesh=...) with a named fn
            if tc is node and node.args and isinstance(node.args[0], ast.Name):
                wrapped.setdefault(node.args[0].id, node)
    return {name: call for name, call in wrapped.items() if name in funcs}


def _traced_params(fn: ast.FunctionDef, wrap: ast.Call) -> Set[str]:
    statics = _static_names(wrap)
    nums = _static_nums(wrap)
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    traced = {name for i, name in enumerate(pos)
              if i not in nums and name not in statics}
    traced.update(a.arg for a in fn.args.kwonlyargs if a.arg not in statics)
    traced.discard("self")
    return traced


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in walk_fast(node) if isinstance(n, ast.Name)}


def _is_none_check(test: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` -- concrete at trace time."""
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
            and any(isinstance(c, ast.Constant) and c.value is None
                    for c in [test.left, *test.comparators]))


@register("TJA006", "tracer-safety")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    marked = f"/{ctx.path}"
    if not any(d in marked for d in SCOPE_DIRS):
        return []
    findings: List[Finding] = []
    funcs = {n.name: n
             for n in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef)}

    def emit(node: ast.AST, severity: str, msg: str) -> None:
        findings.append(Finding("TJA006", "tracer-safety", ctx.path,
                                node.lineno, node.col_offset, severity, msg))

    for name, wrap in _traced_functions(
            ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef,
                        ast.Call)).items():
        fn = funcs[name]
        traced = _traced_params(fn, wrap)
        for node in walk_fast(fn):
            if isinstance(node, ast.If) and not _is_none_check(node.test):
                if (isinstance(node.test, ast.Compare)
                        and _names_in(node.test) & traced):
                    emit(node.test, ERROR,
                         f"Python 'if' on traced value(s) "
                         f"{sorted(_names_in(node.test) & traced)} inside "
                         f"jit-wrapped '{name}'; use lax.cond/lax.select or "
                         "mark the argument static")
            elif isinstance(node, ast.While):
                hits = _names_in(node.test) & traced
                if hits:
                    emit(node.test, ERROR,
                         f"Python 'while' on traced value(s) {sorted(hits)} "
                         f"inside jit-wrapped '{name}'; use lax.while_loop")
            elif isinstance(node, ast.Call):
                cf = node.func
                if (isinstance(cf, ast.Name) and cf.id in HOST_SYNC_BUILTINS
                        and node.args and _names_in(node.args[0]) & traced):
                    emit(node, ERROR,
                         f"{cf.id}() on a traced value inside jit-wrapped "
                         f"'{name}' forces a host sync (ConcretizationError "
                         "under jit); keep it on-device")
                elif (isinstance(cf, ast.Attribute)
                        and cf.attr in HOST_SYNC_METHODS
                        and _names_in(cf.value) & traced):
                    emit(node, ERROR,
                         f".{cf.attr}() on a traced value inside jit-wrapped "
                         f"'{name}' forces a device->host round-trip per call")
                elif isinstance(cf, ast.Name) and cf.id == "print":
                    emit(node, WARNING,
                         f"print() inside jit-wrapped '{name}' runs at trace "
                         "time, not per step; use jax.debug.print")
    return findings
