"""TJA002 lock-discipline: a static race detector for the reconcile plane.

In any class that creates a ``threading.Lock``/``RLock``/``Condition`` (the
workqueue, informers, expectations, tracker, metrics registry), an attribute
is *guarded* when some method mutates it inside ``with self._lock:``.  Mixed
discipline -- the same attribute also mutated outside the lock elsewhere --
is exactly the latent race ISSUE.md cites: it works until two workqueue
threads interleave, then silently corrupts controller state.

Heuristics that keep the pass quiet on correct code:

- ``__init__`` is exempt (the object is not yet shared during construction).
- Methods whose name ends in ``_locked`` are exempt (the caller-holds-lock
  helper convention).
- Only attributes *sometimes* guarded are checked; a field never touched
  under the lock is assumed single-threaded by design.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: Method names on a ``self.X`` receiver that mutate X in place.
MUTATORS = {
    "append", "appendleft", "add", "insert", "extend", "update",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "setdefault", "push", "heappush", "heappop", "sort", "reverse",
}


def _is_lock_factory(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name in LOCK_FACTORIES


def _self_attr(node: ast.expr) -> Optional[str]:
    """'x' when node is exactly ``self.x``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _mutated_attrs(stmt: ast.stmt) -> List[Tuple[str, ast.AST]]:
    """(attr name, node) for every ``self.X`` mutated by this statement
    (not descending into nested statements -- the walker handles nesting)."""
    out: List[Tuple[str, ast.AST]] = []

    def target_attrs(target: ast.expr):
        # self.x = ..., self.x[k] = ..., and tuple-unpack combinations
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                target_attrs(el)
            return
        if isinstance(target, (ast.Subscript, ast.Starred)):
            target_attrs(target.value)
            return
        attr = _self_attr(target)
        if attr is not None:
            out.append((attr, target))

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            target_attrs(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if stmt.target is not None:
            target_attrs(stmt.target)
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            target_attrs(t)
    elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        fn = stmt.value.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
            attr = _self_attr(fn.value)
            if attr is not None:
                out.append((attr, stmt.value))
    return out


class _MethodWalker:
    """Walk one method body tracking whether each statement runs under a
    ``with self.<lock>:`` for any of the class's lock attributes."""

    def __init__(self, lock_attrs: Set[str]):
        self.lock_attrs = lock_attrs
        self.guarded: List[Tuple[str, ast.AST]] = []    # mutations under lock
        self.unguarded: List[Tuple[str, ast.AST]] = []  # mutations outside

    def _holds_lock(self, node: ast.With) -> bool:
        for item in node.items:
            expr = item.context_expr
            # ``with self._lock:`` and ``with self._cond:`` -- also accept
            # ``with self._lock.acquire_timeout(...)``-style wrappers.
            attr = _self_attr(expr)
            if attr is None and isinstance(expr, ast.Call):
                fn = expr.func
                if isinstance(fn, ast.Attribute):
                    attr = _self_attr(fn.value)
            if attr in self.lock_attrs:
                return True
        return False

    def walk(self, stmts: List[ast.stmt], locked: bool) -> None:
        for stmt in stmts:
            for attr, node in _mutated_attrs(stmt):
                (self.guarded if locked else self.unguarded).append((attr, node))
            if isinstance(stmt, ast.With):
                self.walk(stmt.body, locked or self._holds_lock(stmt))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # A closure defined here may run on another thread later:
                # treat its body as NOT holding the lock.
                self.walk(stmt.body, False)
            else:
                for field in ("body", "orelse", "finalbody", "handlers"):
                    children = getattr(stmt, field, None)
                    if not children:
                        continue
                    for child in children:
                        if isinstance(child, ast.ExceptHandler):
                            self.walk(child.body, locked)
                        elif isinstance(child, ast.stmt):
                            self.walk([child], locked)


@register("TJA002", "lock-discipline")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    findings: List[Finding] = []
    # One sweep over the file's cached Assign bucket, attributed to the
    # nearest enclosing class via the shared parents map -- re-walking
    # every method body per class was a visible slice of the lint budget.
    parents = ctx.parents
    lock_attrs_by_class: Dict[int, Set[str]] = {}
    for node in ctx.by_type(ast.Assign):
        if not _is_lock_factory(node.value):
            continue
        attrs = {a for a in (_self_attr(t) for t in node.targets)
                 if a is not None}
        if not attrs:
            continue
        anc = parents.get(id(node))
        while anc is not None and not isinstance(anc, ast.ClassDef):
            anc = parents.get(id(anc))
        if anc is not None:
            lock_attrs_by_class.setdefault(id(anc), set()).update(attrs)
    for cls in ctx.by_type(ast.ClassDef):
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        lock_attrs = lock_attrs_by_class.get(id(cls), set())
        if not lock_attrs:
            continue

        guarded: Set[str] = set()
        per_method: Dict[str, _MethodWalker] = {}
        for m in methods:
            w = _MethodWalker(lock_attrs)
            w.walk(m.body, locked=False)
            per_method[m.name] = w
            guarded.update(attr for attr, _node in w.guarded)
        guarded -= lock_attrs  # reassigning the lock itself is not data

        for m in methods:
            if m.name == "__init__" or m.name.endswith("_locked"):
                continue
            for attr, node in per_method[m.name].unguarded:
                if attr not in guarded:
                    continue
                findings.append(Finding(
                    "TJA002", "lock-discipline", ctx.path,
                    getattr(node, "lineno", m.lineno),
                    getattr(node, "col_offset", 0), ERROR,
                    f"{cls.name}.{m.name} mutates self.{attr} outside "
                    f"'with self.{sorted(lock_attrs)[0]}:' but other code "
                    f"mutates it under the lock (data race)"))
    return findings
