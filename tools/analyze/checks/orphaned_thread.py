"""TJA008 orphaned-thread: every ``threading.Thread`` is either a daemon or
provably joined.

A non-daemon thread with no ``join()`` outlives its owner silently: process
shutdown blocks in the interpreter's thread-join teardown (the operator
hangs on SIGTERM until the kubelet SIGKILLs it), and under pytest a leaked
thread keeps running into later tests.  Compliance evidence, per
construction site:

1. a ``daemon=True`` keyword on the constructor;
2. ``<name>.join(`` somewhere in the same file, where ``<name>`` is the
   variable or attribute the thread was assigned to (``self._th`` matches
   ``_th.join``); or
3. threads collected in a container that is join-swept -- ``for t in
   threads: t.join()`` / ``[t.join() for t in threads]`` credits
   ``threads``.

The analysis is file-local and name-based by design: a thread handed across
modules for someone else to join is exactly the ownership ambiguity the
pass exists to flag -- waive it with a reason if the cross-module join is
intentional.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register


def _leaf_name(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_thread_ctor(call: ast.Call) -> bool:
    """``threading.Thread(...)`` / ``th.Thread(...)`` / bare ``Thread(...)``;
    leaf-name match so module aliases work without import resolution."""
    return _leaf_name(call.func) == "Thread"


def _daemon_kwarg_ok(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            # daemon=<expr> counts unless it is literally False.
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
    return False


def _collect_evidence(nodes: list) -> Set[str]:
    """Names credited with a join (directly, via a join-sweep over them, or
    via an explicit ``<name>.daemon = True`` after construction)."""
    # comprehension/for variable -> iterated container name
    var_to_iter: Dict[str, str] = {}
    for node in nodes:
        if isinstance(node, ast.For):
            tgt, it = node.target, node.iter
            if isinstance(tgt, ast.Name) and isinstance(it, ast.Name):
                var_to_iter[tgt.id] = it.id
        elif isinstance(node, ast.comprehension):
            tgt, it = node.target, node.iter
            if isinstance(tgt, ast.Name) and isinstance(it, ast.Name):
                var_to_iter[tgt.id] = it.id
    credited: Set[str] = set()
    for node in nodes:
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            leaf = _leaf_name(node.func.value)
            if leaf:
                credited.add(leaf)
                if leaf in var_to_iter:
                    credited.add(var_to_iter[leaf])
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
              and isinstance(node.targets[0], ast.Attribute)
              and node.targets[0].attr == "daemon"
              and isinstance(node.value, ast.Constant)
              and node.value.value is True):
            leaf = _leaf_name(node.targets[0].value)
            if leaf:
                credited.add(leaf)
    return credited


def _bindings(nodes: list) -> Dict[int, str]:
    """id(Thread Call) -> leaf name it is bound to, covering direct
    assignment, assignment of a comprehension building threads, and
    ``container.append(Thread(...))``."""
    bound: Dict[int, str] = {}
    for node in nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            leaf = _leaf_name(node.targets[0])
            if not leaf:
                continue
            value = node.value
            if isinstance(value, ast.Call) and _is_thread_ctor(value):
                bound[id(value)] = leaf
            elif isinstance(value, (ast.ListComp, ast.SetComp)):
                if (isinstance(value.elt, ast.Call)
                        and _is_thread_ctor(value.elt)):
                    bound[id(value.elt)] = leaf
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "append" and len(node.args) == 1
              and isinstance(node.args[0], ast.Call)
              and _is_thread_ctor(node.args[0])):
            leaf = _leaf_name(node.func.value)
            if leaf:
                bound[id(node.args[0])] = leaf
    return bound


@register("TJA008", "orphaned-thread")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None or "Thread(" not in ctx.source:
        return []
    credited = _collect_evidence(ctx.nodes)
    bound = _bindings(ctx.nodes)
    findings: List[Finding] = []
    for node in ctx.nodes:
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        if _daemon_kwarg_ok(node):
            continue
        name: Optional[str] = bound.get(id(node))
        if name is not None and name in credited:
            continue
        hint = (f"bound to {name!r} which is never joined" if name
                else "never bound to a name, so it cannot be joined")
        findings.append(Finding(
            "TJA008", "orphaned-thread", ctx.path, node.lineno,
            node.col_offset, ERROR,
            f"threading.Thread without daemon=True and no join ({hint}); "
            "a leaked non-daemon thread blocks interpreter shutdown -- "
            "pass daemon=True, join it, or waive with the ownership "
            "rationale"))
    return findings
