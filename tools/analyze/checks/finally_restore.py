"""TJA019 finally-state-restore: restores that skip the exception path.

The toggle-around-a-blocking-region idiom::

    self._suspended = True
    drain_replicas()          # can raise
    self._suspended = False   # never runs on the exception path

leaves the flag stuck when the region raises: the watchdog stays suspended
forever, the pacer never resumes, the guard never re-arms.  The restore
belongs in a ``finally`` -- and because cfg.py duplicates ``finally`` bodies
onto the exceptional copies, a correctly-written restore is an ordinary kill
on the exception path and produces no finding.

Formulation (forward *may* analysis, facts = individual toggle assignments):

- **gen** at ``X = <constant>`` / ``self.a = <constant>`` where the constant
  is a bool/None sentinel (toggles, not arithmetic);
- **kill** at any other assignment to the same target (the restore);
  ``AugAssign`` neither gens nor kills -- counters are not toggles.

A toggle is flagged iff it is live into ``exc_exit`` but **not** live into
``exit``: every normal path restores it (so the author demonstrably intends
restoration) while some exception path does not.  The not-live-at-exit
requirement is what keeps ordinary init-then-update assignments quiet.
``__init__`` is excluded wholesale -- constructors initialize, they don't
toggle.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.analyze import dataflow
from tools.analyze.findings import (FileContext, Finding, WARNING,
                                    _LOCAL_BARRIERS)
from tools.analyze.runner import register
from tools.analyze.checks._flow import functions_of


def _toggle_target(stmt: ast.AST) -> Optional[str]:
    """'name' / 'self.attr' for a single-target assignment, else None."""
    if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
        return None
    t = stmt.targets[0]
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return f"self.{t.attr}"
    return None


def _is_sentinel(value: ast.expr) -> bool:
    return isinstance(value, ast.Constant) \
        and (value.value is None or isinstance(value.value, bool))


class _Toggles(dataflow.Analysis):
    """Facts: (target, id(assign stmt), lineno)."""

    may = True

    def gen(self, stmt: ast.AST):
        tgt = _toggle_target(stmt)
        if tgt is not None and _is_sentinel(stmt.value):
            return [(tgt, id(stmt), stmt.lineno)]
        return []

    def kill(self, stmt: ast.AST, facts):
        tgt = _toggle_target(stmt)
        if tgt is None:
            return []
        return [f for f in facts if f[0] == tgt and f[1] != id(stmt)]


@register("TJA019", "finally-state-restore")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    findings: List[Finding] = []
    analysis = _Toggles()
    # Cheap gate: >= 2 sentinel assignments to one target, else no
    # set/restore pair can exist and the CFG build is wasted.  The counts
    # come from one sweep of the file's Assign bucket attributed to the
    # owning function by parent-chain (#assigns x depth), not a rewalk of
    # every function body (#all-nodes) -- the rewalks were this pass's
    # dominant cost on toggle-free files, i.e. nearly all of them.
    parents = ctx.parents
    barriers = _LOCAL_BARRIERS
    counts_by_fn = {}
    for node in ctx.by_type(ast.Assign):
        tgt = _toggle_target(node)
        if tgt is None:
            continue
        cur = parents.get(id(node))
        while cur is not None and cur.__class__ not in barriers:
            cur = parents.get(id(cur))
        if cur is None:
            continue
        counts = counts_by_fn.setdefault(id(cur), {})
        entry = counts.setdefault(tgt, [0, 0])
        entry[0] += 1
        if _is_sentinel(node.value):
            entry[1] += 1
    for fn in functions_of(ctx):
        if fn.name == "__init__":
            continue
        counts = counts_by_fn.get(id(fn), {})
        # A finding needs a *sentinel* set (the only gen) plus a second
        # assignment to the same target (the restore): plain rebind pairs
        # (``x = f(); x = g(x)``) can never fire, and they are the common
        # case -- requiring the sentinel cuts ~80% of the CFG+solve work.
        if not any(c[0] >= 2 and c[1] for c in counts.values()):
            continue
        cfg = ctx.cfg(fn)
        sol = dataflow.solve(cfg, analysis)
        stuck = sol.in_of(cfg.exc_exit) - sol.in_of(cfg.exit)
        for tgt, _sid, line in sorted(stuck, key=lambda f: f[2]):
            if counts.get(tgt, (0, 0))[0] < 2:
                continue  # no restore anywhere: init, not a toggle pair
            findings.append(Finding(
                "TJA019", "finally-state-restore", ctx.path, line, 0,
                WARNING,
                f"{tgt} is toggled in {fn.name}() and restored on the "
                f"normal path but not on the exception path; move the "
                f"restore into a finally block"))
    return findings
