"""TJA009 status-write-discipline: every job phase/condition mutation goes
through the status machine in ``controller/status.py``.

The condition list is an append-or-refresh state machine with invariants
(latest condition authoritative, older ones flipped to False, completed jobs
frozen) that only ``set_condition``/``update_job_conditions`` maintain.  A
raw ``job.status.phase = ...`` or ``job.status.conditions.append(...)`` at a
call site bypasses the completed-job guard and the condition flip, producing
status histories no consumer can interpret.  Flagged shapes:

1. assignment to ``<job>.status.phase`` or ``<job>.status.conditions``; and
2. ``<job>.status.conditions.append(...)`` / ``.extend`` / ``.insert``.

A receiver participates when the root of the attribute chain is a name
containing ``job`` (``job``, ``fresh_job``, ``trainingjob``...) or is the
bare ``status`` object itself (the pass-the-status-subobject idiom used by
the status helpers).  Pod/node status writes (``pod.status.phase = ...`` in
the runtimes) are a different, unguarded API and are not flagged.

The implementing helpers themselves -- ``set_condition``,
``update_job_conditions`` and ``new_condition`` in ``controller/status.py``
-- are exempt: they ARE the discipline.  Scope is operator code only
(``trainingjob_operator_tpu/``); tests construct status fixtures directly.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from tools.analyze.findings import ERROR, FileContext, Finding
from tools.analyze.runner import register

#: Attribute names on ``.status`` whose mutation is the state machine's job.
_GUARDED_FIELDS = ("phase", "conditions")

#: List-mutating methods on ``.status.conditions``.
_MUTATORS = ("append", "extend", "insert")

#: (path suffix, function names) exempt because they implement the machine.
_EXEMPT = ("trainingjob_operator_tpu/controller/status.py",
           ("set_condition", "update_job_conditions", "new_condition"))


def _chain_root(node: ast.expr) -> Optional[ast.Name]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node if isinstance(node, ast.Name) else None


def _is_job_status(node: ast.expr) -> bool:
    """True for ``<job-ish>.status`` or the bare ``status`` name."""
    if isinstance(node, ast.Name):
        return node.id == "status"
    if isinstance(node, ast.Attribute) and node.attr == "status":
        root = _chain_root(node)
        return root is not None and "job" in root.id.lower()
    return False


def _guarded_target(node: ast.expr) -> Optional[str]:
    """'phase' / 'conditions' when ``node`` is a guarded status attribute."""
    if (isinstance(node, ast.Attribute) and node.attr in _GUARDED_FIELDS
            and _is_job_status(node.value)):
        return node.attr
    return None


def _exempt_lines(ctx: FileContext) -> Set[Tuple[int, int]]:
    suffix, names = _EXEMPT
    if not ctx.path.endswith(suffix):
        return set()
    spans: Set[Tuple[int, int]] = set()
    for node in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef):
        if node.name in names:
            spans.add((node.lineno, max(getattr(node, "end_lineno", node.lineno),
                                        node.lineno)))
    return spans


@register("TJA009", "status-write-discipline")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None or not ctx.path.startswith("trainingjob_operator_tpu/"):
        return []
    if ".status." not in ctx.source and "status.phase" not in ctx.source:
        return []
    exempt = _exempt_lines(ctx)

    def exempted(line: int) -> bool:
        return any(lo <= line <= hi for lo, hi in exempt)

    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        if exempted(node.lineno):
            return
        findings.append(Finding(
            "TJA009", "status-write-discipline", ctx.path, node.lineno,
            node.col_offset, ERROR,
            f"direct {what} bypasses the status machine; route the change "
            "through update_job_conditions/set_condition "
            "(controller/status.py) so the completed-job guard and "
            "condition-flip invariants hold"))

    for node in ctx.by_type(ast.Assign, ast.AugAssign, ast.Call):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for target in targets:
                field = _guarded_target(target)
                if field:
                    flag(target, f"write to job .status.{field}")
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _MUTATORS
              and _guarded_target(node.func.value) == "conditions"):
            flag(node, f".status.conditions.{node.func.attr}() call")
    return findings
