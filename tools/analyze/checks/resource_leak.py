"""TJA015 resource-leak: acquired but not released on some exit path.

The operator's long-lived processes hold OS resources behind plain locals:
telemetry TCP sockets, pserver listen sockets, handler threads, spans.  A
function that binds one (``server = socket.socket()``) and then hits an
exception -- or an early ``return`` -- before ``close()`` leaks it; under a
controller that restarts replicas for a living, those leaks compound until
the pod dies on fd exhaustion (the reference's restart machine makes this a
steady-state code path, not a rarity).

This is the first CFG/dataflow consumer (cfg.py, dataflow.py): a forward
*may* analysis whose facts are live acquisitions.

- **gen**: ``name = <factory>(...)`` where the factory is a known resource
  constructor (sockets, ``open``, HTTP connections, ``Popen``, ``Thread``,
  ``.span()``).  ``with factory() as x:`` never generates -- the ``with``
  releases it.
- **kill**: a release/teardown method on the name (``close``/``join``/
  ``start``/...), rebinding, or any *escape*: the name returned, yielded,
  stored into an attribute/subscript/container, passed as a call argument,
  or aliased -- ownership left the function, the leak (if any) is someone
  else's contract.
- On **exception edges** the engine drops gen (dataflow.py): if the factory
  call itself raises, there is nothing to leak.

A fact still live entering ``exc_exit`` leaks on an exception path; live
entering ``exit`` it leaks on a normal return path (ps_worker's timeout
``return 1`` with the listen socket open was the motivating catch).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from tools.analyze import dataflow
from tools.analyze.findings import (ERROR, FileContext, Finding,
                                    _LOCAL_BARRIERS, walk_fast)
from tools.analyze.runner import register
from tools.analyze.checks._flow import call_dotted, functions_of
from tools.analyze.cfg import stmt_expressions

#: factory (bare or dotted callee name) -> resource kind.
FACTORIES = {
    "socket.socket": "socket",
    "socket.create_connection": "socket",
    "create_connection": "socket",
    "open": "file",
    "HTTPConnection": "connection",
    "HTTPSConnection": "connection",
    "subprocess.Popen": "process",
    "Popen": "process",
    "threading.Thread": "thread",
    "Thread": "thread",
}

#: Method names on the resource that count as release/handoff.
RELEASE_ATTRS = {"close", "detach", "shutdown", "terminate", "kill", "wait",
                 "communicate", "start", "join", "cancel", "stop", "release",
                 "end", "finish", "__exit__"}


def _factory_kind(value: ast.expr) -> str:
    if not isinstance(value, ast.Call):
        return ""
    dotted = call_dotted(value)
    if dotted is None:
        return ""
    kind = FACTORIES.get(dotted)
    if kind:
        return kind
    if dotted.endswith(".span") and "." in dotted:
        return "span"
    return ""


def _bound_names(stmt: ast.AST) -> Iterator[str]:
    """Names (re)bound by a statement: assignment targets, loop targets,
    ``with ... as``, ``except ... as``."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    elif isinstance(stmt, ast.ExceptHandler) and stmt.name:
        yield stmt.name
        return
    for t in targets:
        for node in walk_fast(t):
            if isinstance(node, ast.Name):
                yield node.id


def _escaped_names(stmt: ast.AST) -> Set[str]:
    """Names used anywhere a reference can outlive the statement: as a call
    argument, in a returned/stored/aliased value, in a container literal.
    The one *non*-escaping use is as the receiver of an attribute access
    (``s.recv(...)`` keeps ``s`` owned here)."""
    out: Set[str] = set()
    stack: List[ast.AST] = list(stmt_expressions(stmt))
    # Assignment *value* escapes (alias/store); bare Name targets are
    # rebinding, not escape, and stmt_expressions already includes targets
    # only for Assign -- drop those.
    if isinstance(stmt, ast.Assign):
        stack = [stmt.value] + [t for t in stmt.targets
                                if not isinstance(t, ast.Name)]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                          ast.Name):
            continue  # receiver use: s.close() / s.family
        if isinstance(node, ast.Name):
            out.add(node.id)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _released_names(stmt: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for expr in stmt_expressions(stmt):
        for node in walk_fast(expr):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.attr in RELEASE_ATTRS):
                out.add(node.func.value.id)
    return out


class _Live(dataflow.Analysis):
    """Facts: (name, acquisition lineno, kind)."""

    may = True

    def gen(self, stmt: ast.AST):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            kind = _factory_kind(stmt.value)
            if kind:
                return [(stmt.targets[0].id, stmt.lineno, kind)]
        return []

    def kill(self, stmt: ast.AST, facts):
        dead = set(_bound_names(stmt)) | _released_names(stmt) \
            | _escaped_names(stmt)
        return [f for f in facts if f[0] in dead]


@register("TJA015", "resource-leak")
def check(ctx: FileContext) -> List[Finding]:
    if ctx.tree is None:
        return []
    findings: List[Finding] = []
    analysis = _Live()
    # Factory-bearing functions from one sweep of the file's Assign bucket
    # (gen only fires at ``name = <factory>(...)``, so only assignment
    # values can matter), attributed to the owning def by parent-chain
    # (#assigns x depth) instead of a walk_local sweep per function
    # (#all-nodes): the sweeps dominated this pass on factory-free files,
    # i.e. nearly all of them.  Owner == nearest barrier reproduces
    # walk_local's membership exactly.
    parents = ctx.parents
    barriers = _LOCAL_BARRIERS
    has_factory = set()
    for stmt in ctx.by_type(ast.Assign):
        if len(stmt.targets) == 1 and stmt.targets[0].__class__ is ast.Name \
                and _factory_kind(stmt.value):
            cur = parents.get(id(stmt))
            while cur is not None and cur.__class__ not in barriers:
                cur = parents.get(id(cur))
            if cur is not None:
                has_factory.add(id(cur))
    for fn in functions_of(ctx):
        if id(fn) not in has_factory:
            continue  # no factory anywhere: skip the CFG build entirely
        cfg = ctx.cfg(fn)
        sol = dataflow.solve(cfg, analysis)
        leaks: Dict[Tuple[str, int, str], List[str]] = {}
        for fact in sorted(sol.in_of(cfg.exc_exit)):
            leaks.setdefault(fact, []).append("an exception path")
        for fact in sorted(sol.in_of(cfg.exit)):
            leaks.setdefault(fact, []).append("a return path")
        for (name, line, kind), paths in sorted(leaks.items()):
            findings.append(Finding(
                "TJA015", "resource-leak", ctx.path, line, 0, ERROR,
                f"{kind} {name!r} acquired in {fn.name}() is not released on "
                f"{' or '.join(paths)}; close it in a finally/with so "
                f"restarts don't leak it"))
    return findings
