"""Whole-program concurrency model: thread roles, MHP, lock-sets.

TJA027 classifies *singletons*; the lock passes (TJA002/TJA010/TJA016)
reason about locks in a vacuum.  Neither answers the question ROADMAP
item 3 (controller scale-out) actually turns on: **which threads run
concurrently against which shared state, and under which locks**.  This
layer models the process's real thread topology once per
``ProjectContext`` (BUILD_COUNT-memoized like ``cfg``/``jit_boundary``/
``determinism``) and the TJA028-TJA032 passes consume it:

1. **Thread-role inference.**  Every ``threading.Thread(target=...)``
   spawn site in non-test code becomes a role; the target callable is
   resolved (``self._loop`` through mixin composites, module functions,
   nested ``def`` pump bodies, ``obj.method`` through inferred
   constructor types) and its interprocedural call closure is computed
   over the same ``MethodSummary`` call graph TJA010 uses (one shared
   ``CallResolver``).  The main thread joins as a synthetic role rooted
   at the ``cmd`` entry point.

2. **May-happen-in-parallel (MHP).**  Two distinct roles may run in
   parallel unless their spawn sites live in different workload
   programs (``workloads/serve.py`` threads never share a process with
   ``workloads/train.py`` threads); a role MHPs with *itself* iff
   multiple instances can exist -- spawned in a loop, spawned per
   constructed instance (``__init__``/multi-site constructors, e.g. one
   pump per workqueue), or spawned by a role that is itself multiple
   (one runtime poller per tracked job, created by the worker pool).

3. **Lock-sets.**  ``lock_set(path, line)`` is the set of lock ids
   lexically held at a statement -- ``with`` regions resolved through
   ``CallResolver.lock_id`` (mixin-aware), built lazily per file and
   only for files a pass actually flags, so the 2 s lint budget holds.

Everything is witness-based and conservative in the same sense as the
rest of the analyzer: dynamic spawns, executor pools, and cross-process
shared memory are invisible; code reachable from *no* role contributes
no concurrency evidence (it may be dead, test-only, or CLI-only -- the
passes only report what the model can prove runs in parallel).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.analyze.findings import FileContext
from tools.analyze.jit_boundary import is_test_path
from tools.analyze.project import (
    CallResolver, ClassInfo, MethodSummary, ModuleInfo, ProjectContext,
    _BodyWalker, _dotted, _mutable_kind, _self_attr,
)

#: Times a ThreadModel was actually constructed (not returned from the
#: per-ProjectContext memo) -- tests assert built-once per run.
BUILD_COUNT = 0

PKG = "trainingjob_operator_tpu"

#: Inventory kinds that are bare containers/counters (no methods of their
#: own to lock): the race passes reason about their accesses directly.
#: Class-instance singletons own their locking and are vetted through
#: their class's methods instead (TJA032 evidence).
BARE_CONTAINER_KINDS = frozenset({
    "dict", "list", "set", "bytearray", "deque", "defaultdict",
    "Counter", "OrderedDict", "ChainMap", "count",
})

#: Method names that constitute a stop path on a role's owning class.
STOP_METHOD_NAMES = ("stop", "shutdown", "shut_down", "close",
                     "request_stop")

#: Method-name prefixes treated as reads when called on a shared object;
#: everything else is conservatively a mutation.  Canonical copy (the
#: TJA027 shard-state pass imports it).
READ_PREFIXES = (
    "get", "is_", "has_", "peek", "depth", "render", "snapshot", "to_",
    "export", "format", "iter", "keys", "values", "items", "copy",
    "summary", "describe", "count", "index", "armed", "bundle", "list",
    "read", "collect", "lines", "span", "window", "traces",
)


def is_read_method(method: str) -> bool:
    return method.startswith(READ_PREFIXES)


def locked_by_convention(qual: str) -> bool:
    """The ``_locked`` suffix convention: a method named ``*_locked`` is
    only ever called with its object's lock already held, so its accesses
    are guarded even though no ``with`` region is lexically visible."""
    return qual.rpartition(".")[2].endswith("_locked")


@dataclass
class ThreadRole:
    """One spawn site (or the synthetic main thread)."""
    name: str
    kind: str = "thread"                   # "thread" | "main"
    spawn_path: str = ""
    spawn_line: int = 0
    entries: Tuple[str, ...] = ()          # resolved target summary quals
    target: str = ""                       # raw target text for the report
    daemon: bool = False
    multi: bool = False                    # >1 instance may exist (self-MHP)
    domain: str = "shared"                 # process-compatibility group
    owner_qual: str = ""                   # qual of the spawning function
    owner_class: Optional[str] = None      # class qual owning the spawn site
    owner_method: str = ""
    thread_attr: Optional[str] = None      # ``self.X = Thread(...)``
    thread_list_attr: Optional[str] = None # ``self.X.append(t)``
    closure: FrozenSet[str] = frozenset()


@dataclass(frozen=True)
class Access:
    """One witnessed touch of a shared object."""
    path: str
    line: int
    via: str
    write: bool
    qual: str                              # owning summary qual ("" = module)


def _domain_of(module_name: str) -> str:
    """Process-compatibility group for a spawn site.  Each workload
    program is its own process; everything else (controller, client,
    obs, runtime, utils -- importable from any process) is 'shared'."""
    parts = module_name.split(".")
    for i, part in enumerate(parts):
        if part == "workloads" and i + 1 < len(parts):
            return f"workloads.{parts[i + 1]}"
    return "shared"


def _event_factory(value: ast.expr) -> bool:
    """True for ``threading.Event()``-shaped constructor calls."""
    if not isinstance(value, ast.Call):
        return False
    d = _dotted(value.func)
    return d is not None and d.rpartition(".")[2] == "Event"


class ThreadModel:
    """The built model.  Construct via ``model(pc)``, never directly."""

    def __init__(self, pc: ProjectContext):
        self.pc = pc
        self.resolver = CallResolver(pc)
        self.roles: Dict[str, ThreadRole] = {}
        #: class qual -> {container attr -> definition line}.
        self.container_attrs: Dict[str, Dict[str, int]] = {}
        #: class qual -> set of ``threading.Event()`` attr names.
        self.event_attrs: Dict[str, Set[str]] = {}
        #: qual -> (mod, class, summary) for every summary incl. synthetics.
        self._summaries: Dict[str, Tuple[ModuleInfo, Optional[ClassInfo],
                                         MethodSummary]] = {}
        self._qual_roles: Dict[str, Set[str]] = {}
        self._lock_regions: Dict[str, List[Tuple[int, int, str]]] = {}
        self._fn_spans: Dict[str, List[Tuple[int, int, str]]] = {}
        self._role_locks: Dict[str, FrozenSet[str]] = {}
        self._closure_memo: Dict[Tuple[str, ...], FrozenSet[str]] = {}
        self._attr_accesses: Optional[
            Dict[Tuple[str, str], List[Access]]] = None
        self._spawns: List[dict] = []      # raw spawn records (for widening)

    # -- construction --------------------------------------------------------

    def _build(self) -> None:
        self._index_summaries()
        for rel, ctx in sorted(self.pc.files.items()):
            if ctx.tree is None or is_test_path(rel):
                continue
            mod = self.pc.module_of_path(rel)
            if mod is None:
                continue
            self._collect_file(rel, ctx, mod)
        self._add_main_role()
        for role in self.roles.values():
            role.closure = self._closure(role.entries)
        self._refine_multi()
        for name, role in self.roles.items():
            for q in role.closure:
                self._qual_roles.setdefault(q, set()).add(name)

    def _index_summaries(self) -> None:
        for mod in self.pc.modules.values():
            for s in mod.fn_summaries.values():
                self._summaries[s.qual] = (mod, None, s)
            for ci in mod.classes.values():
                for s in ci.summaries.values():
                    self._summaries[s.qual] = (mod, ci, s)

    def _collect_file(self, rel: str, ctx: FileContext,
                      mod: ModuleInfo) -> None:
        by_node = {id(ci.node): ci for ci in mod.classes.values()}
        parents = ctx.parents

        def owner_class_of(node: ast.AST) -> Optional[ClassInfo]:
            anc = parents.get(id(node))
            while anc is not None:
                if isinstance(anc, ast.ClassDef):
                    return by_node.get(id(anc))
                anc = parents.get(id(anc))
            return None

        # Container/Event attribute inference (``self.X = {}`` /
        # ``self.X = threading.Event()``), one sweep over the cached
        # Assign bucket -- same trick as ProjectContext._index_module.
        for sub in ctx.by_type(ast.Assign):
            kind = _mutable_kind(sub.value)
            is_event = kind is None and _event_factory(sub.value)
            if kind is None and not is_event:
                continue
            attrs = [a for a in (_self_attr(t) for t in sub.targets)
                     if a is not None]
            if not attrs:
                continue
            owner = owner_class_of(sub)
            if owner is None:
                continue
            for attr in attrs:
                if is_event:
                    self.event_attrs.setdefault(owner.qual, set()).add(attr)
                elif attr not in owner.lock_attrs:
                    self.container_attrs.setdefault(owner.qual, {})\
                        .setdefault(attr, sub.lineno)

        if "Thread(" not in ctx.source:
            return
        for call in ctx.by_type(ast.Call):
            if not self._thread_ctor(call, mod):
                continue
            self._record_spawn(rel, ctx, mod, by_node, call)

    @staticmethod
    def _thread_ctor(call: ast.Call, mod: ModuleInfo) -> bool:
        d = _dotted(call.func)
        if d is None or d.rpartition(".")[2] != "Thread":
            return False
        if d == "Thread":
            return mod.imports.get("Thread", "threading.Thread") \
                == "threading.Thread"
        head = d.partition(".")[0]
        return mod.imports.get(head, head) == "threading"

    def _record_spawn(self, rel: str, ctx: FileContext, mod: ModuleInfo,
                      by_node: Dict[int, ClassInfo], call: ast.Call) -> None:
        parents = ctx.parents
        names: List[str] = []
        in_loop = False
        fn_node: Optional[ast.AST] = None
        owner_ci: Optional[ClassInfo] = None
        anc = parents.get(id(call))
        while anc is not None:
            if isinstance(anc, (ast.For, ast.While)) and fn_node is None:
                in_loop = True
            elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if fn_node is None:
                    fn_node = anc
                names.append(anc.name)
            elif isinstance(anc, ast.ClassDef):
                if owner_ci is None:
                    owner_ci = by_node.get(id(anc))
                names.append(anc.name)
            anc = parents.get(id(anc))
        names.reverse()
        owner_qual = mod.name + ("." + ".".join(names) if names else "")
        owner_method = fn_node.name if fn_node is not None else ""

        target = None
        daemon = False
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "daemon":
                daemon = isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True

        thread_attr = thread_list = local = None
        p = parents.get(id(call))
        if isinstance(p, ast.Assign) and len(p.targets) == 1:
            t = p.targets[0]
            a = _self_attr(t)
            if a is not None:
                thread_attr = a
            elif isinstance(t, ast.Name):
                local = t.id
        elif isinstance(p, ast.Call) and isinstance(p.func, ast.Attribute) \
                and p.func.attr == "append":
            thread_list = _self_attr(p.func.value)
        if local is not None and fn_node is not None:
            for n in ast.walk(fn_node):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "append" and n.args \
                        and isinstance(n.args[0], ast.Name) \
                        and n.args[0].id == local:
                    a = _self_attr(n.func.value)
                    if a is not None:
                        thread_list = a
                        break

        entries, target_text = self._resolve_target(
            mod, owner_ci, fn_node, owner_qual, target)
        rel_mod = mod.name[len(PKG) + 1:] \
            if mod.name.startswith(PKG + ".") else mod.name
        leaf = target_text.rpartition(".")[2] or "thread"
        name = f"{leaf}@{rel_mod}:{call.lineno}"
        self.roles[name] = ThreadRole(
            name=name, spawn_path=rel, spawn_line=call.lineno,
            entries=tuple(sorted(entries)), target=target_text,
            daemon=daemon, multi=in_loop or owner_method == "__init__",
            domain=_domain_of(mod.name), owner_qual=owner_qual,
            owner_class=owner_ci.qual if owner_ci is not None else None,
            owner_method=owner_method, thread_attr=thread_attr,
            thread_list_attr=thread_list)
        self._spawns.append({"path": rel, "line": call.lineno})

    def _resolve_target(self, mod: ModuleInfo, owner_ci: Optional[ClassInfo],
                        fn_node: Optional[ast.AST], owner_qual: str,
                        target: Optional[ast.expr]) -> Tuple[List[str], str]:
        """(entry summary quals, raw target text) for a spawn's target."""
        if target is None:
            return [], "<no-target>"
        text = _dotted(target) or "<dynamic>"
        attr = _self_attr(target)
        if attr is not None and owner_ci is not None:
            hits = self.resolver.callee_summaries(mod, owner_ci,
                                                  ("self", attr))
            return [s.qual for _m, _c, s in hits], text
        if isinstance(target, ast.Name):
            # A nested pump body defined in the spawning function (or an
            # enclosing one) is a deferred execution context the project
            # summaries deliberately exclude; synthesize its summary here
            # so the role still gets a closure.
            if fn_node is not None:
                for n in ast.walk(fn_node):
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))\
                            and n is not fn_node and n.name == target.id:
                        qual = f"{owner_qual}.{n.name}"
                        if qual not in self._summaries:
                            locks: Set[str] = set()
                            if owner_ci is not None:
                                for c in self.pc.mro_classes(owner_ci):
                                    locks |= set(c.lock_attrs)
                            s = MethodSummary(qual=qual, node=n)
                            _BodyWalker(s, locks,
                                        set(mod.module_locks)).walk(n, [])
                            self._summaries[qual] = (mod, owner_ci, s)
                        return [qual], text
            hits = self.resolver.callee_summaries(mod, owner_ci,
                                                  ("name", target.id))
            return [s.qual for _m, _c, s in hits], text
        if isinstance(target, ast.Attribute):
            recv = target.value
            leaf = recv.id if isinstance(recv, ast.Name) else (
                _self_attr(recv) or (recv.attr
                                     if isinstance(recv, ast.Attribute)
                                     else None))
            if leaf is not None:
                hits = self.resolver.callee_summaries(
                    mod, owner_ci, ("attr", leaf, target.attr))
                return [s.qual for _m, _c, s in hits], text
        return [], text

    def _add_main_role(self) -> None:
        """The main thread, rooted at the operator ``cmd`` entry point."""
        entries: List[str] = []
        path, line = "", 0
        for mod in self.pc.modules.values():
            if "cmd" not in mod.name.split("."):
                continue
            s = mod.fn_summaries.get("main")
            if s is not None:
                entries.append(s.qual)
                if mod.ctx is not None and not path:
                    path = mod.ctx.path
                    line = getattr(s.node, "lineno", 0)
        self.roles["main"] = ThreadRole(
            name="main", kind="main", spawn_path=path, spawn_line=line,
            entries=tuple(sorted(entries)), target="<main>", domain="shared")

    def _closure(self, entries: Tuple[str, ...]) -> FrozenSet[str]:
        key = tuple(sorted(entries))
        got = self._closure_memo.get(key)
        if got is not None:
            return got
        seen: Set[str] = set(entries)
        stack = [q for q in entries if q in self._summaries]
        while stack:
            rec = self._summaries.get(stack.pop())
            if rec is None:
                continue
            mod, cls, s = rec
            for call in {c[:-1] for c in s.calls}:
                for _m, _c, s2 in self.resolver.callee_summaries(
                        mod, cls, call):
                    if s2.qual not in seen:
                        seen.add(s2.qual)
                        stack.append(s2.qual)
        got = frozenset(seen)
        self._closure_memo[key] = got
        return got

    def _refine_multi(self) -> None:
        """Mark roles whose owning object is constructed more than once
        (or by an already-multiple role) as multi-instance."""
        interesting: Dict[str, List[str]] = {}   # ctor leaf -> role names
        for name, role in self.roles.items():
            if role.multi or role.owner_class is None:
                continue
            ci = self.pc.classes.get(role.owner_class)
            if ci is None:
                continue
            for c in self.resolver.composites(ci):
                interesting.setdefault(c.name, []).append(name)
        if not interesting:
            return
        sites: Dict[str, List[Tuple[bool, str]]] = {}  # role -> (in_loop, qual)
        for rel, ctx in self.pc.files.items():
            if ctx.tree is None or is_test_path(rel):
                continue
            parents = ctx.parents
            for call in ctx.by_type(ast.Call):
                d = _dotted(call.func)
                if d is None:
                    continue
                roles = interesting.get(d.rpartition(".")[2])
                if not roles:
                    continue
                in_loop = False
                names: List[str] = []
                anc = parents.get(id(call))
                fn_seen = False
                while anc is not None:
                    if isinstance(anc, (ast.For, ast.While)) and not fn_seen:
                        in_loop = True
                    elif isinstance(anc, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        fn_seen = True
                        names.append(anc.name)
                    elif isinstance(anc, ast.ClassDef):
                        names.append(anc.name)
                    anc = parents.get(id(anc))
                names.reverse()
                mod = self.pc.module_of_path(rel)
                qual = (mod.name + ("." + ".".join(names) if names else "")
                        if mod is not None else "")
                for rname in roles:
                    sites.setdefault(rname, []).append((in_loop, qual))
        for _round in range(2):   # one propagation hop: worker-made makers
            changed = False
            for rname, recs in sites.items():
                role = self.roles[rname]
                if role.multi:
                    continue
                multi = len(recs) >= 2 or any(in_loop for in_loop, _q in recs)
                if not multi:
                    for _in_loop, qual in recs:
                        q = self._norm_qual(qual)
                        for other in self.roles.values():
                            if other.multi and q in other.closure:
                                multi = True
                                break
                        if multi:
                            break
                if multi:
                    role.multi = True
                    changed = True
            if not changed:
                break

    # -- queries -------------------------------------------------------------

    def mhp(self, a: str, b: str) -> bool:
        """May roles ``a`` and ``b`` run in parallel?"""
        ra, rb = self.roles.get(a), self.roles.get(b)
        if ra is None or rb is None:
            return False
        if a == b:
            return ra.multi
        if ra.domain == rb.domain:
            return True
        return "shared" in (ra.domain, rb.domain)

    def _norm_qual(self, qual: str) -> str:
        """Strip nested-def components until a known summary qual."""
        q = qual
        while q and q not in self._summaries:
            head, _, _leaf = q.rpartition(".")
            if not head:
                return qual
            q = head
        return q or qual

    def roles_of(self, qual: str) -> FrozenSet[str]:
        """Role names whose closure contains (the summary owning) ``qual``."""
        if not qual:
            return frozenset()
        got = self._qual_roles.get(qual)
        if got is None:
            got = self._qual_roles.get(self._norm_qual(qual), set())
        return frozenset(got)

    def owner_qual(self, path: str, line: int) -> str:
        """Qual of the innermost function containing ``path:line``
        ('' for module level)."""
        spans = self._fn_spans.get(path)
        if spans is None:
            spans = []
            ctx = self.pc.files.get(path)
            if ctx is not None and ctx.tree is not None:
                mod = self.pc.module_of_path(path)
                base = mod.name if mod is not None else ""
                parents = ctx.parents
                for kind in (ast.FunctionDef, ast.AsyncFunctionDef):
                    for fn in ctx.by_type(kind):
                        names = [fn.name]
                        anc = parents.get(id(fn))
                        while anc is not None:
                            if isinstance(anc, (ast.FunctionDef,
                                                ast.AsyncFunctionDef,
                                                ast.ClassDef)):
                                names.append(anc.name)
                            anc = parents.get(id(anc))
                        names.reverse()
                        qual = (base + "." if base else "") + ".".join(names)
                        spans.append((fn.lineno, fn.end_lineno or fn.lineno,
                                      qual))
            self._fn_spans[path] = spans
        best, best_start = "", -1
        for start, end, qual in spans:
            if start <= line <= end and start > best_start:
                best, best_start = qual, start
        return best

    def roles_at(self, path: str, line: int) -> FrozenSet[str]:
        return self.roles_of(self.owner_qual(path, line))

    def lock_set(self, path: str, line: int) -> FrozenSet[str]:
        """Lock ids lexically held at ``path:line`` (``with`` regions,
        mixin-aware).  Built lazily per file."""
        regions = self._lock_regions.get(path)
        if regions is None:
            regions = self._build_regions(path)
            self._lock_regions[path] = regions
        return frozenset(lid for start, end, lid in regions
                         if start <= line <= end)

    def _build_regions(self, path: str) -> List[Tuple[int, int, str]]:
        out: List[Tuple[int, int, str]] = []
        ctx = self.pc.files.get(path)
        if ctx is None or ctx.tree is None:
            return out
        mod = self.pc.module_of_path(path)
        if mod is None:
            return out
        by_node = {id(ci.node): ci for ci in mod.classes.values()}
        parents = ctx.parents
        for kind in (ast.With, ast.AsyncWith):
            for w in ctx.by_type(kind):
                names: List[str] = []
                for item in w.items:
                    expr = item.context_expr
                    attr = _self_attr(expr)
                    if attr is not None:
                        names.append(attr)
                    elif isinstance(expr, ast.Name) \
                            and expr.id in mod.module_locks:
                        names.append(expr.id)
                if not names or not w.body:
                    continue
                owner = None
                anc = parents.get(id(w))
                while anc is not None:
                    if isinstance(anc, ast.ClassDef):
                        owner = by_node.get(id(anc))
                        break
                    anc = parents.get(id(anc))
                for name in names:
                    hit = self.resolver.lock_id(mod, owner, name)
                    if hit is not None:
                        out.append((w.body[0].lineno,
                                    w.end_lineno or w.lineno, hit[0]))
        return out

    def role_lock_ids(self, role_name: str) -> FrozenSet[str]:
        """Every lock id a role's closure may acquire."""
        got = self._role_locks.get(role_name)
        if got is None:
            acc: Set[str] = set()
            role = self.roles.get(role_name)
            for q in (role.closure if role is not None else ()):
                rec = self._summaries.get(q)
                if rec is None:
                    continue
                mod, cls, s = rec
                for name in s.acquires:
                    hit = self.resolver.lock_id(mod, cls, name)
                    if hit is not None:
                        acc.add(hit[0])
            got = frozenset(acc)
            self._role_locks[role_name] = got
        return got

    def stop_summaries(self, class_qual: str) \
            -> List[Tuple[str, MethodSummary]]:
        """(defining file path, summary) for every stop-path method of a
        class (across composites)."""
        ci = self.pc.classes.get(class_qual)
        if ci is None:
            return []
        out: List[Tuple[str, MethodSummary]] = []
        seen: Set[str] = set()
        for k in self.resolver.composites(ci):
            for c in self.pc.mro_classes(k):
                for name in STOP_METHOD_NAMES:
                    s = c.summaries.get(name)
                    if s is not None and s.qual not in seen:
                        seen.add(s.qual)
                        owner_mod = self.pc.modules.get(c.module)
                        path = owner_mod.ctx.path \
                            if owner_mod is not None \
                            and owner_mod.ctx is not None else ""
                        out.append((path, s))
        return out

    def has_stop_path(self, class_qual: Optional[str]) -> bool:
        return bool(class_qual and self.stop_summaries(class_qual))

    def condition_kind(self, path: str, node: ast.AST,
                       receiver: ast.expr) -> Optional[str]:
        """'Condition'/'Lock'/'RLock' when ``receiver`` names a lock-
        factory attribute or module lock, 'Event' for an Event attr,
        else None."""
        mod = self.pc.module_of_path(path)
        if mod is None:
            return None
        attr = _self_attr(receiver)
        if attr is None:
            if isinstance(receiver, ast.Name):
                return mod.module_locks.get(receiver.id)
            return None
        ctx = self.pc.files.get(path)
        owner = None
        if ctx is not None:
            by_node = {id(ci.node): ci for ci in mod.classes.values()}
            anc = ctx.parents.get(id(node))
            while anc is not None:
                if isinstance(anc, ast.ClassDef):
                    owner = by_node.get(id(anc))
                    break
                anc = ctx.parents.get(id(anc))
        if owner is None:
            return None
        for k in [owner] + self.resolver.composites(owner):
            for c in self.pc.mro_classes(k):
                kind = c.lock_attrs.get(attr)
                if kind is not None:
                    return kind
                if attr in self.event_attrs.get(c.qual, ()):
                    return "Event"
        return None

    # -- shared instance attributes ------------------------------------------

    def attr_accesses(self) -> Dict[Tuple[str, str], List[Access]]:
        """(defining class qual, attr) -> accesses, for container attrs of
        classes whose methods span >= 2 roles.  Computed lazily (only the
        race passes pay for it)."""
        if self._attr_accesses is not None:
            return self._attr_accesses
        acc: Dict[Tuple[str, str], List[Access]] = {}
        attr_names = set()
        for attrs in self.container_attrs.values():
            attr_names.update(attrs)
        if not attr_names:
            self._attr_accesses = acc
            return acc
        for rel, ctx in self.pc.files.items():
            if ctx.tree is None or is_test_path(rel):
                continue
            mod = self.pc.module_of_path(rel)
            if mod is None or not mod.classes:
                continue
            self._collect_attr_file(rel, ctx, mod, attr_names, acc)
        self._attr_accesses = acc
        return acc

    def _defining_class(self, owner: ClassInfo, attr: str) -> Optional[str]:
        """Class qual whose code creates container ``attr``, looked up
        through the full composite (a mixin method's ``self`` is really
        the composing class, whose ``__init__`` may own the attribute)."""
        for k in self.resolver.composites(owner):
            for c in self.pc.mro_classes(k):
                if attr in self.container_attrs.get(c.qual, ()):
                    return c.qual
        return None

    def _collect_attr_file(self, rel: str, ctx: FileContext, mod: ModuleInfo,
                           attr_names: Set[str],
                           acc: Dict[Tuple[str, str], List[Access]]) -> None:
        by_node = {id(ci.node): ci for ci in mod.classes.values()}
        parents = ctx.parents

        def note(node: ast.AST, target: ast.expr, via: str,
                 write: bool) -> None:
            attr = _self_attr(target)
            if attr is None or attr not in attr_names:
                return
            names: List[str] = []
            owner = None
            anc = parents.get(id(node))
            while anc is not None:
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    names.append(anc.name)
                elif isinstance(anc, ast.ClassDef):
                    if owner is None:
                        owner = by_node.get(id(anc))
                    names.append(anc.name)
                anc = parents.get(id(anc))
            if owner is None or not names:
                return
            if names[0] in ("__init__", "__new__"):
                return   # construction happens-before any spawn
            defining = self._defining_class(owner, attr)
            if defining is None:
                return
            names.reverse()
            qual = f"{mod.name}." + ".".join(names)
            acc.setdefault((defining, attr), []).append(
                Access(path=rel, line=node.lineno, via=via, write=write,
                       qual=qual))

        for call in ctx.by_type(ast.Call):
            fn = call.func
            if isinstance(fn, ast.Attribute):
                note(call, fn.value, f"{fn.attr}()",
                     not is_read_method(fn.attr))
            elif isinstance(fn, ast.Name) and fn.id == "next" and call.args:
                note(call, call.args[0], "next()", True)
        for node in ctx.by_type(ast.Assign):
            for t in node.targets:
                if not isinstance(t, (ast.Subscript, ast.Attribute)):
                    continue
                if _self_attr(t) is not None:
                    note(node, t, "rebind", True)
                else:
                    note(node, t.value, "store", True)
        for node in ctx.by_type(ast.AugAssign):
            t = node.target
            if isinstance(t, (ast.Subscript, ast.Attribute)):
                if _self_attr(t) is not None:
                    note(node, t, "augmented store", True)
                else:
                    note(node, t.value, "augmented store", True)
        for node in ctx.by_type(ast.Delete):
            for t in node.targets:
                if isinstance(t, (ast.Subscript, ast.Attribute)) \
                        and _self_attr(t) is None:
                    note(node, t.value, "delete", True)
        for node in ctx.by_type(ast.Subscript):
            if isinstance(node.ctx, ast.Load):
                note(node, node.value, "subscript", False)
        for node in ctx.by_type(ast.For):
            note(node, node.iter, "iterate", False)

    # -- report --------------------------------------------------------------

    def describe(self) -> dict:
        """Roles + MHP matrix for the thread_model.json report."""
        names = sorted(self.roles)
        roles = []
        for n in names:
            r = self.roles[n]
            roles.append({
                "name": n,
                "kind": r.kind,
                "spawn": {"path": r.spawn_path, "line": r.spawn_line},
                "target": r.target,
                "entries": sorted(r.entries),
                "daemon": r.daemon,
                "multi": r.multi,
                "domain": r.domain,
                "owner": r.owner_qual or None,
                "owner_class": r.owner_class,
                "thread_attr": r.thread_attr or r.thread_list_attr,
                "closure_size": len(r.closure),
                "closure": sorted(r.closure),
            })
        mhp = {a: sorted(b for b in names if self.mhp(a, b)) for a in names}
        return {"roles": roles, "mhp": mhp}


def model(pc: ProjectContext) -> ThreadModel:
    """The memoized per-ProjectContext concurrency model."""
    got = getattr(pc, "_thread_model", None)
    if got is None:
        global BUILD_COUNT
        BUILD_COUNT += 1
        got = ThreadModel(pc)
        got._build()
        pc._thread_model = got
    return got
