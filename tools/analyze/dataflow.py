"""Worklist dataflow over the per-function CFGs (cfg.py).

A tiny classic gen-kill framework: a client subclasses ``Analysis``, names a
direction (forward/backward) and a meet (may=union / must=intersection), and
gets per-block ``in``/``out``/``exc_out`` fact sets from ``solve``.

The one non-textbook rule -- load-bearing for TJA015/TJA019 -- is how facts
flow along *exception* edges.  A statement that raises did not complete:

    exc_fact(stmt) = facts_before(stmt) - kill(stmt)        # gen NOT applied

If ``s = socket.socket()`` itself raises, the binding never happened, so the
acquisition fact must not escape onto the exception path; if ``s.close()``
raises, the socket is in teardown and we still treat it as released.  A
block's ``exc_out`` is the union of that per-statement residue over its
raising statements, and exceptional edges propagate ``exc_out`` where normal
edges propagate ``out``.

Must-analyses use optimistic iteration: blocks start at TOP (an "everything
holds" sentinel) and TOP operands are skipped in the meet, the standard
treatment for intersection lattices with unreachable joins.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, Iterator, List, Tuple

from tools.analyze.cfg import CFG, Block, EXC_KINDS

#: "Not yet computed" for must-analyses; distinct from the empty set.
TOP = None


class Analysis:
    """Gen-kill dataflow client.  Facts are hashable opaque values."""

    #: "forward" or "backward".
    direction = "forward"
    #: True -> meet is union (may / exists-a-path); False -> intersection
    #: (must / all-paths).
    may = True

    def gen(self, stmt: ast.AST) -> Iterable:
        return ()

    def kill(self, stmt: ast.AST, facts: FrozenSet) -> Iterable:
        """Facts killed by ``stmt``.  ``facts`` are the facts flowing in,
        for clients whose kill depends on what is live (e.g. "any fact for
        this variable name")."""
        return ()

    def entry_facts(self, cfg: CFG) -> Iterable:
        """Facts at the function entry (backward: at the exits)."""
        return ()


class Solution:
    """Per-block fact sets.  For forward analyses ``block_in`` is at block
    entry, ``block_out`` at exit, ``exc_out`` what escapes on exceptions."""

    def __init__(self, analysis: Analysis):
        self.analysis = analysis
        self.block_in: Dict[int, FrozenSet] = {}
        self.block_out: Dict[int, FrozenSet] = {}
        self.exc_out: Dict[int, FrozenSet] = {}

    def in_of(self, block: Block) -> FrozenSet:
        facts = self.block_in.get(block.bid, TOP)
        return frozenset() if facts is TOP else facts

    def out_of(self, block: Block) -> FrozenSet:
        facts = self.block_out.get(block.bid, TOP)
        return frozenset() if facts is TOP else facts

    def exc_of(self, block: Block) -> FrozenSet:
        facts = self.exc_out.get(block.bid, TOP)
        return frozenset() if facts is TOP else facts

    def walk(self, block: Block) -> Iterator[Tuple[ast.AST, FrozenSet,
                                                   FrozenSet]]:
        """(stmt, facts_before, facts_after) for each statement of a block,
        in forward order -- the statement-granular view clients report from."""
        facts = self.in_of(block)
        for stmt in block.stmts:
            killed = frozenset(self.analysis.kill(stmt, facts))
            after = (facts - killed) | frozenset(self.analysis.gen(stmt))
            yield stmt, facts, after
            facts = after


def _transfer(analysis: Analysis, block: Block,
              facts: FrozenSet) -> Tuple[FrozenSet, FrozenSet]:
    """Forward transfer of one block: (out, exc_out)."""
    exc_acc: set = set()
    any_raising = False
    for stmt, raising in zip(block.stmts, block.raising):
        killed = frozenset(analysis.kill(stmt, facts))
        if raising:
            any_raising = True
            exc_acc |= (facts - killed)
        facts = (facts - killed) | frozenset(analysis.gen(stmt))
    if not any_raising:
        # Dispatch blocks (and any empty block with an exc successor) pass
        # their in-facts through unchanged on the exceptional edge.
        exc_acc = set(facts)
    return facts, frozenset(exc_acc)


def _meet(analysis: Analysis, contributions: List[FrozenSet]) -> FrozenSet:
    live = [c for c in contributions if c is not TOP]
    if not live:
        return TOP
    if analysis.may:
        out: set = set()
        for c in live:
            out |= c
        return frozenset(out)
    out = set(live[0])
    for c in live[1:]:
        out &= c
    return frozenset(out)


def solve(cfg: CFG, analysis: Analysis) -> Solution:
    sol = Solution(analysis)
    if analysis.direction == "backward":
        return _solve_backward(cfg, analysis, sol)

    sol.block_in = {b.bid: TOP for b in cfg.blocks}
    sol.block_out = {b.bid: TOP for b in cfg.blocks}
    sol.exc_out = {b.bid: TOP for b in cfg.blocks}
    sol.block_in[cfg.entry.bid] = frozenset(analysis.entry_facts(cfg))

    work = list(cfg.blocks)
    on_work = {b.bid for b in work}
    while work:
        block = work.pop(0)
        on_work.discard(block.bid)
        if block is not cfg.entry:
            contribs = []
            for pred, kind in block.preds:
                src = sol.exc_out if kind in EXC_KINDS else sol.block_out
                contribs.append(src[pred.bid])
            new_in = _meet(analysis, contribs)
            if new_in is TOP:
                continue  # no reachable predecessor computed yet
            sol.block_in[block.bid] = new_in
        facts = sol.block_in[block.bid]
        if facts is TOP:
            continue
        out, exc = _transfer(analysis, block, facts)
        if out != sol.block_out[block.bid] or exc != sol.exc_out[block.bid]:
            sol.block_out[block.bid] = out
            sol.exc_out[block.bid] = exc
            for succ, _kind in block.succs:
                if succ.bid not in on_work:
                    on_work.add(succ.bid)
                    work.append(succ)
    return sol


def _solve_backward(cfg: CFG, analysis: Analysis, sol: Solution) -> Solution:
    """Backward may-analysis (liveness-style).  ``block_in`` holds facts at
    block *entry* as seen walking backward (i.e. what is demanded before the
    block); exceptional edges contribute like normal ones."""
    sol.block_in = {b.bid: TOP for b in cfg.blocks}
    sol.block_out = {b.bid: TOP for b in cfg.blocks}
    exits = frozenset(analysis.entry_facts(cfg))
    for b in (cfg.exit, cfg.exc_exit):
        sol.block_out[b.bid] = exits

    work = list(cfg.blocks)
    on_work = {b.bid for b in work}
    while work:
        block = work.pop(0)
        on_work.discard(block.bid)
        if block not in (cfg.exit, cfg.exc_exit):
            contribs = [sol.block_in[s.bid] for s, _k in block.succs]
            new_out = _meet(analysis, contribs)
            if new_out is TOP:
                continue
            sol.block_out[block.bid] = new_out
        facts = sol.block_out[block.bid]
        if facts is TOP:
            continue
        for stmt in reversed(block.stmts):
            killed = frozenset(analysis.kill(stmt, facts))
            facts = (facts - killed) | frozenset(analysis.gen(stmt))
        if facts != sol.block_in[block.bid]:
            sol.block_in[block.bid] = facts
            for pred, _kind in block.preds:
                if pred.bid not in on_work:
                    on_work.add(pred.bid)
                    work.append(pred)
    return sol
