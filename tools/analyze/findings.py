"""Finding model shared by all analyzer passes.

A finding is one defect at one source location.  Its *fingerprint*
deliberately excludes the line number: baselines must survive unrelated edits
above a grandfathered finding, so identity is (check, file, message, index-
among-identical-messages-in-file) -- the scheme flake8/ratchet-style baselines
converge on.
"""

from __future__ import annotations

import ast
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ERROR = "error"
WARNING = "warning"

_SEVERITY_RANK = {ERROR: 0, WARNING: 1}


@dataclass(frozen=True)
class Finding:
    check_id: str       # e.g. "TJA001"
    check_name: str     # e.g. "py-compat"
    path: str           # repo-relative, forward slashes
    line: int
    col: int
    severity: str       # ERROR | WARNING
    message: str

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def sort_key(self):
        return (self.path, self.line, self.col,
                _SEVERITY_RANK.get(self.severity, 9), self.check_id)


#: Grammar-token singletons (expr_context/operator/boolop/unaryop/cmpop):
#: childless nodes CPython's parser interns as shared instances -- ~35% of
#: ``ast.walk``'s yield on this tree.  Every pass reads them as attributes
#: of their owner (``node.ctx``, ``node.op``), never out of a walk, and
#: their shared identity already made per-instance ``parents``/bucket
#: entries meaningless.  Every walk this module builds skips them.
_TOKEN_NODES = frozenset(
    cls
    for base in (ast.expr_context, ast.boolop, ast.operator, ast.unaryop,
                 ast.cmpop)
    for cls in base.__subclasses__())

#: Node classes whose every field is a scalar or a token: enumerating their
#: fields can never push a child.  Name + Constant alone are ~1/3 of the
#: non-token nodes on this tree, so the fused walk skips their field loop
#: outright (a visible slice of the lint budget).
_LEAF_NODES = frozenset((
    ast.Name, ast.Constant, ast.Pass, ast.Break, ast.Continue,
    ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal, ast.alias,
    ast.MatchSingleton, ast.TypeIgnore))


def walk_fast(root) -> list:
    """``ast.walk`` equivalent returning a list (same BFS order, minus the
    ``_TOKEN_NODES`` singletons), with the per-node iter_child_nodes
    generator pair inlined away.  The passes call this on tens of thousands
    of small subtrees (handlers, with-items, statement bodies); the
    generator resumption overhead of the stdlib version was a visible slice
    of the lint budget.  The list is cached on ``root``: the path-sensitive
    passes re-walk the same handlers and statements (~40% repeat rate), and
    the callers are all read-only scans."""
    cached = getattr(root, "_tja_walk", None)
    if cached is not None:
        return cached
    out = [root]
    isinst, AST = isinstance, ast.AST
    tokens = _TOKEN_NODES
    leaves = _LEAF_NODES
    push = out.append
    i = 0
    while i < len(out):
        n = out[i]
        i += 1
        if n.__class__ in leaves:
            continue
        d = n.__dict__
        for name in n._fields:
            v = d.get(name)
            if v.__class__ is list:
                for item in v:
                    if isinst(item, AST) and item.__class__ not in tokens:
                        push(item)
            elif isinst(v, AST) and v.__class__ not in tokens:
                push(v)
    root._tja_walk = out
    return out


#: Deferred-execution scopes: ``walk_local`` (checks/_flow.py) stops at
#: these, and ``FileContext._build_walk`` prefills each one's own-body walk
#: during its single fused sweep.  One definition so the two stay in sync.
_LOCAL_BARRIERS = {ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                   ast.ClassDef}

#: One-slot cache for cfg.build_cfg, filled on first FileContext.cfg() call
#: (module-level import would be a cycle: cfg.py imports findings).
_BUILD_CFG: list = [None]


def fingerprint(f: Finding, occurrence: int) -> str:
    """Stable identity for baselining: line-number independent."""
    raw = f"{f.check_id}|{f.path}|{f.message}|{occurrence}"
    return hashlib.sha256(raw.encode()).hexdigest()[:16]


def fingerprint_all(findings: List[Finding]) -> Dict[str, Finding]:
    """Fingerprint a finding list, disambiguating identical messages in the
    same file by occurrence index (document order)."""
    seen: Dict[str, int] = {}
    out: Dict[str, Finding] = {}
    for f in sorted(findings, key=Finding.sort_key):
        key = f"{f.check_id}|{f.path}|{f.message}"
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        out[fingerprint(f, occ)] = f
    return out


@dataclass
class FileContext:
    """Everything a check needs about one source file, parsed once."""
    path: str                 # repo-relative
    abs_path: str
    source: str
    lines: List[str] = field(default_factory=list)
    tree: object = None       # ast.Module | None when the file doesn't parse
    _nodes: Optional[list] = field(default=None, repr=False)
    _buckets: Optional[dict] = field(default=None, repr=False)
    _cfgs: Optional[dict] = field(default=None, repr=False)
    _parents: Optional[dict] = field(default=None, repr=False)

    @property
    def nodes(self) -> list:
        """Every AST node in the file (``ast.walk`` order, minus the
        ``_TOKEN_NODES`` singletons), computed once and
        shared by all passes.  With a dozen passes each re-walking every
        tree, the walk itself dominates analyzer wall-clock; passes that
        scan the whole file iterate this list instead."""
        if self._nodes is None:
            self._build_walk()
        return self._nodes

    @property
    def parents(self) -> dict:
        """id(node) -> parent for every node, recorded during the same
        single sweep that fills ``nodes`` (a second full-tree pass just for
        parent links measurably ate into the 2 s lint budget)."""
        if self._parents is None:
            self._build_walk()
        return self._parents

    def _build_walk(self) -> None:
        # Manual BFS equivalent to ``ast.walk`` (same node order) with the
        # child enumeration inlined: iter_child_nodes/iter_fields are two
        # generators per node, and over ~450k nodes their resumption
        # overhead alone is a visible slice of the wall-clock budget.
        # The per-class buckets ``by_type`` serves are filled in the same
        # sweep -- a second full pass over ``nodes`` just to bucket them
        # was the next-largest slice once the walk itself was fused.
        # The per-function ``walk_local`` caches (checks/_flow.py) are
        # also prefilled here: each node is appended to the list of its
        # nearest enclosing def/class/lambda, so the path-sensitive and
        # determinism passes never re-walk a function body they reach
        # through a built FileContext (the re-walks were the largest
        # remaining slice of the 2 s budget after the walk was fused).
        nodes: list = []
        parents: dict = {}
        buckets: dict = {}
        if self.tree is not None:
            isinst, AST = isinstance, ast.AST
            barriers = _LOCAL_BARRIERS
            tokens = _TOKEN_NODES
            leaves = _LEAF_NODES
            push = nodes.append
            push(self.tree)
            # owners[i] is the _tja_local_walk list of nodes[i]'s nearest
            # enclosing barrier (None at module level), maintained in
            # lockstep with the queue.
            owners: list = [None]
            opush = owners.append
            i = 0
            # ``nodes`` doubles as the BFS queue (index-walked, never
            # popped) -- same order as ``ast.walk``, no deque traffic.
            while i < len(nodes):
                n = nodes[i]
                own = owners[i]
                i += 1
                cls = n.__class__
                try:
                    buckets[cls].append(n)
                except KeyError:
                    buckets[cls] = [n]
                if own is not None:
                    own.append(n)
                if cls in leaves:
                    continue
                if cls in barriers:
                    # Children belong to this barrier's own-body walk; the
                    # list is complete by the time _build_walk returns, and
                    # walk_local's membership semantics are order-blind
                    # (BFS here vs its lazy DFS).
                    own = n._tja_local_walk = []
                d = n.__dict__
                for name in n._fields:
                    v = d.get(name)
                    if v.__class__ is list:
                        for item in v:
                            if isinst(item, AST) \
                                    and item.__class__ not in tokens:
                                parents[id(item)] = n
                                push(item)
                                opush(own)
                    elif isinst(v, AST) and v.__class__ not in tokens:
                        parents[id(v)] = n
                        push(v)
                        opush(own)
        self._nodes = nodes
        self._parents = parents
        self._buckets = buckets

    def by_type(self, *types: type) -> list:
        """Nodes of the given exact AST classes, bucketed during the same
        sweep that fills ``nodes``.  Most passes scan for one or two node
        kinds; iterating just those buckets skips the isinstance sieve over
        the other ~95% of nodes.  Order is walk order within a class,
        concatenated across classes."""
        if self._buckets is None:
            self._build_walk()
        if len(types) == 1:
            return self._buckets.get(types[0], [])
        out: list = []
        for t in types:
            out.extend(self._buckets.get(t, ()))
        return out

    def cfg(self, func_node):
        """Control-flow graph of one function (cfg.py), built lazily and
        memoized per AST node: the five path-sensitive passes (TJA015+) ask
        for the same functions, and the project passes see the same
        FileContext objects the runner parsed, so each function body is
        built exactly once per run (the 2 s budget depends on it)."""
        build_cfg = _BUILD_CFG[0]
        if build_cfg is None:
            # Import deferred to first use (cfg.py imports this module); the
            # cached slot keeps the import machinery off the per-call path --
            # a function-local ``from`` import here re-ran _handle_fromlist
            # once per cfg() call, a visible slice of the lint budget.
            from tools.analyze.cfg import build_cfg
            _BUILD_CFG[0] = build_cfg
        if self._cfgs is None:
            self._cfgs = {}
        key = id(func_node)
        got = self._cfgs.get(key)
        if got is None:
            got = self._cfgs[key] = build_cfg(func_node)
        return got

    def waived(self, line: int, check_name: str) -> bool:
        """True when ``line`` (or the line above) carries an explicit waiver:

            # analyzer: allow[<check-name>] <reason>

        ``allow[*]`` waives every check on that line.  The tag may sit on the
        flagged line itself or anywhere in the contiguous comment block
        immediately above it (waiver rationales are encouraged to span
        lines).  The reason text is required by convention but not enforced.
        """
        def tagged(text: str) -> bool:
            return (f"analyzer: allow[{check_name}]" in text
                    or "analyzer: allow[*]" in text)

        if not 1 <= line <= len(self.lines):
            return False
        if tagged(self.lines[line - 1]):
            return True
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            if tagged(self.lines[ln - 1]):
                return True
            ln -= 1
        return False
