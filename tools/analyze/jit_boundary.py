"""Jit-boundary semantic layer: traced-region closure + hot-loop map.

The serving and training planes stake their throughput claims on three
static disciplines (docs/SERVING.md, docs/RECOVERY.md): fixed-shape traced
executables (no admission-pattern recompiles), no host synchronization
inside the step/decode hot loops beyond the deliberate fences, and buffer
donation where a step function is state-in/state-out.  The bench gates
enforce those dynamically; this module gives the analyzer the two facts it
needs to enforce them *statically*:

- the **traced-region closure**: every function reachable from a
  ``jax.jit`` / ``pjit`` / ``pmap`` / ``shard_map`` / ``jax.lax.scan`` site,
  with the wrapping call site and its static/donated argnums recorded.
  Entries may be decorated defs, ``jit(fn)`` / ``jit(partial(fn, ...))``
  wrap calls (including cross-module ``jit(lambda ...: mod.fn(...))``
  shapes -- serve.py's three executables), or scan bodies; the closure
  walks calls interprocedurally through the project symbol table.
- the **hot-loop map**: loops that drive a device computation per
  iteration, *seeded from loop-carried device values* -- a loop is hot
  when a value produced by a dispatching call feeds back into a
  dispatching call (``params, opt, loss = step_fn(params, opt, batch)``),
  or when it invokes a *tick function*, one that round-trips object state
  through a dispatching call (``self.cache = self._step_fn(..,
  self.cache, ..)`` -- the serve scheduler).  No file names are special-
  cased; train.py's step loop qualifies because ``step_fn`` is *tainted*
  as a dispatching callable through the ``aot_or_jit`` higher-order chain,
  not because of its path.

"Dispatching callable" is a small fixpoint over the whole tree: jit
bindings seed it; a function that calls one dispatches; a function that
returns one (or returns a nested def that dispatches) yields dispatching
call results; arguments referencing dispatching callables taint the
callee's parameter.  Everything is a conservative, syntactic
approximation, same trade as project.py: dynamic dispatch is invisible,
waivers cover the rest.

The boundary is built **once per run** and memoized on the
``ProjectContext`` instance (like the MRO maps); ``BUILD_COUNT`` exists so
tests can assert that.  All walks reuse the per-file ASTs and
``by_type``/``parents`` caches the runner already built -- no re-parse.

Consumed by TJA020 (recompile-hazard), TJA021 (host-sync-in-hot-loop),
TJA022 (donation-discipline) and TJA023 (impure-capture).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.analyze.findings import FileContext, _TOKEN_NODES, walk_fast
from tools.analyze.project import ProjectContext, _dotted

#: jit-like wrappers: first positional arg (or the decorated def) is traced.
TRACING_WRAPPERS = {"jit", "pjit", "pmap", "shard_map"}

#: builds per process -- the boundary must be computed at most once per
#: ProjectContext (tests assert this, like cfg.BUILD_COUNT).
BUILD_COUNT = 0


def is_test_path(path: str) -> bool:
    """Test-suite files: excluded from the boundary graph (and from every
    pass that consumes it)."""
    return path.startswith("tests/") or "/tests/" in path


def _base_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _int_tuple(call: ast.Call, kwarg: str) -> Tuple[Tuple[int, ...], bool]:
    """(literal ints, kwarg-present) for ``static_argnums=(0, 2)`` shapes."""
    for kw in call.keywords:
        if kw.arg != kwarg:
            continue
        v = kw.value
        parts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        out = tuple(p.value for p in parts
                    if isinstance(p, ast.Constant) and isinstance(p.value, int))
        return out, True
    return (), False


def _str_tuple(call: ast.Call, kwarg: str) -> Tuple[Tuple[str, ...], bool]:
    for kw in call.keywords:
        if kw.arg != kwarg:
            continue
        v = kw.value
        parts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        out = tuple(p.value for p in parts
                    if isinstance(p, ast.Constant) and isinstance(p.value, str))
        return out, True
    return (), False


@dataclass
class JitSite:
    """One place where Python code crosses into a traced computation."""
    path: str
    line: int
    col: int
    kind: str                       # jit|pjit|pmap|shard_map|scan|decorator
    entry_qual: Optional[str] = None   # FnRec qual of the traced entry fn
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    #: the kwarg appeared at all (literal or not) -- "donation considered".
    has_static: bool = False
    has_donate: bool = False
    #: the scope that created the wrapper (module scope for top-level
    #: bindings, ``__init__`` for the serve executables).
    owner_qual: Optional[str] = None
    #: the wrap call itself sits under a loop -- a fresh wrapper (and a
    #: fresh jit cache entry) per iteration.
    wrap_in_loop: bool = False

    def describe(self) -> str:
        return f"{self.kind} site at {self.path}:{self.line}"


@dataclass
class CallRec:
    """One call expression inside a function scope."""
    node: ast.Call
    ref: Optional[tuple]            # ("name", n) | ("self", m)
    #                               # | ("selfattr", attr, m)
    #                               # | ("attr", leaf, m) | ("dotted", full)
    #: flattened assignment targets when the call is an Assign RHS:
    #: plain names as str, ``self.X`` as ("self", X).
    targets: Tuple = ()
    #: enclosing For/While nodes in this scope, outermost first.
    loop_stack: Tuple = ()


@dataclass
class FnRec:
    """Per-scope facts for one def/lambda (nested scopes get their own)."""
    qual: str
    node: ast.AST
    path: str
    module: str
    cls: Optional[str] = None       # enclosing class qual for methods
    parent: Optional[str] = None    # lexically enclosing FnRec qual
    params: List[str] = field(default_factory=list)
    local_names: Set[str] = field(default_factory=set)
    calls: List[CallRec] = field(default_factory=list)
    loops: List[ast.AST] = field(default_factory=list)
    #: local name -> JitSite from ``x = jax.jit(...)`` in this scope.
    jit_bindings: Dict[str, JitSite] = field(default_factory=dict)
    #: local name -> class qual from ``x = ClassName(...)``.
    local_ctors: Dict[str, str] = field(default_factory=dict)
    #: function-level imports, alias -> dotted module/name (serve.py's
    #: ``from ..models import decode as mod`` inside ``__init__``).
    imports: Dict[str, str] = field(default_factory=dict)
    nested: List[str] = field(default_factory=list)
    #: plain names appearing in ``return <name>`` statements.
    returns_names: Set[str] = field(default_factory=set)
    #: every Name read anywhere in a return expression (tuples included) --
    #: coarser than returns_names, used for device-value return taint.
    return_name_refs: Set[str] = field(default_factory=set)
    #: nested-def quals that are returned.
    returns_nested: Set[str] = field(default_factory=set)
    #: names declared global/nonlocal (writes hit enclosing state).
    outer_decls: Set[str] = field(default_factory=set)


@dataclass
class HotLoop:
    path: str
    line: int
    fn_qual: str
    #: the loop-carried device values that made it hot (witness).
    carried: Tuple[str, ...] = ()
    #: human-readable seed description for finding messages.
    via: str = ""

    def describe(self) -> str:
        return f"hot loop at {self.path}:{self.line}"


@dataclass
class Boundary:
    """The memoized product: closure + hot map + dispatch facts."""
    sites: List[JitSite] = field(default_factory=list)
    fns: Dict[str, FnRec] = field(default_factory=dict)
    #: traced-region closure: fn qual -> the sites it is reachable from.
    closure: Dict[str, List[JitSite]] = field(default_factory=dict)
    #: module-level jitted callables: (module, name) -> site;
    #: class-attr jitted callables: ("cls", class qual, attr) -> site.
    bindings: Dict[tuple, JitSite] = field(default_factory=dict)
    #: fn qual -> params known to receive dispatching callables.
    param_taint: Dict[str, Set[str]] = field(default_factory=dict)
    #: fn qual -> local names bound to dispatching call results.
    dispatch_names: Dict[str, Set[str]] = field(default_factory=dict)
    #: fn quals whose invocation dispatches device work.
    dispatching: Set[str] = field(default_factory=set)
    #: fn quals whose return value is a dispatching callable.
    returns_dispatch: Set[str] = field(default_factory=set)
    hot_loops: List[HotLoop] = field(default_factory=list)
    #: fn qual -> witness loop, for functions invoked from a hot loop.
    hot_fns: Dict[str, HotLoop] = field(default_factory=dict)
    #: fn qual -> names/("self", attr) holding device values (hot scope only).
    device_taint: Dict[str, Set] = field(default_factory=dict)
    _pc: Optional[ProjectContext] = None
    #: (fn.qual, ref) -> callee qual; resolution reads only structure fixed
    #: before the fixpoint (defs, imports, ctors), so it never invalidates.
    _resolve_cache: Dict = field(default_factory=dict)
    #: id(CallRec) -> JitSite|None; jit bindings are likewise pre-fixpoint.
    _site_cache: Dict = field(default_factory=dict)
    #: id(CallRec) set of calls already proven to dispatch device work.
    _device_true: Set = field(default_factory=set)
    #: id(CallRec) set of calls that can never dispatch (static verdict).
    _device_false: Set = field(default_factory=set)

    # -- resolution shared by the TJA020-023 passes --------------------------

    def resolve_callee(self, fn: FnRec, ref: tuple) -> Optional[str]:
        """FnRec qual for a call ref as written inside ``fn``, or None."""
        key = (fn.qual, ref)
        try:
            return self._resolve_cache[key]
        except KeyError:
            out = self._resolve_cache[key] = self._resolve_callee(fn, ref)
            return out

    def _resolve_callee(self, fn: FnRec, ref: tuple) -> Optional[str]:
        pc = self._pc
        mod = pc.modules.get(fn.module) if pc else None
        if ref is None or mod is None:
            return None
        kind = ref[0]
        if kind == "name":
            name = ref[1]
            # Lexically visible nested def shadows module scope.
            scope = fn
            while scope is not None:
                cand = f"{scope.qual}.<locals>.{name}"
                if cand in self.fns:
                    return cand
                imp = scope.imports.get(name)
                if imp and imp in self.fns:
                    return imp
                scope = self.fns.get(scope.parent) if scope.parent else None
            if f"{fn.module}.{name}" in self.fns:
                return f"{fn.module}.{name}"
            target = mod.imports.get(name)
            if target and target in self.fns:
                return target
            return None
        if kind == "self":
            return self._method_qual(fn, ref[1])
        if kind == "selfattr":
            attr, meth = ref[1], ref[2]
            ci = pc.classes.get(fn.cls) if fn.cls else None
            if ci is not None:
                ctor = ci.attr_ctors.get(attr)
                if ctor:
                    owner = pc.resolve_class(fn.module, ctor)
                    if owner is not None:
                        return self._class_method_qual(owner, meth)
            return None
        if kind == "attr":
            leaf, meth = ref[1], ref[2]
            ctor = None
            scope = fn
            while scope is not None and ctor is None:
                ctor = scope.local_ctors.get(leaf)
                scope = self.fns.get(scope.parent) if scope.parent else None
            ctor = ctor or mod.global_ctors.get(leaf)
            if ctor:
                owner = (pc.classes.get(ctor)
                         or pc.resolve_class(fn.module, ctor))
                if owner is not None:
                    return self._class_method_qual(owner, meth)
            target = self._scope_import(fn, leaf) or mod.imports.get(leaf)
            if target and f"{target}.{meth}" in self.fns:
                return f"{target}.{meth}"
            return None
        if kind == "dotted":
            full = ref[1]
            head, _, rest = full.partition(".")
            target = mod.imports.get(head)
            if target and f"{target}.{rest}" in self.fns:
                return f"{target}.{rest}"
            return full if full in self.fns else None
        return None

    def _scope_import(self, fn: FnRec, name: str) -> Optional[str]:
        scope = fn
        while scope is not None:
            imp = scope.imports.get(name)
            if imp:
                return imp
            scope = self.fns.get(scope.parent) if scope.parent else None
        return None

    def _method_qual(self, fn: FnRec, meth: str) -> Optional[str]:
        pc = self._pc
        ci = pc.classes.get(fn.cls) if fn.cls else None
        if ci is None:
            return None
        hit = pc.mro_methods(ci).get(meth)
        if hit is None:
            return None
        owner, _node = hit
        qual = f"{owner.qual}.{meth}"
        return qual if qual in self.fns else None

    def _class_method_qual(self, ci, meth: str) -> Optional[str]:
        hit = self._pc.mro_methods(ci).get(meth)
        if hit is None:
            return None
        owner, _node = hit
        qual = f"{owner.qual}.{meth}"
        return qual if qual in self.fns else None

    def site_for_call(self, fn: FnRec, rec: CallRec) -> Optional[JitSite]:
        """The JitSite a call dispatches through, when its callee is a known
        jitted binding (local/enclosing name, ``self._step_fn``, module
        binding, or a jit-decorated function)."""
        key = id(rec)
        try:
            return self._site_cache[key]
        except KeyError:
            out = self._site_cache[key] = self._site_for_call(fn, rec)
            return out

    def _site_for_call(self, fn: FnRec, rec: CallRec) -> Optional[JitSite]:
        ref = rec.ref
        if ref is None:
            return None
        pc = self._pc
        if ref[0] == "name":
            name = ref[1]
            scope = fn
            while scope is not None:
                site = scope.jit_bindings.get(name)
                if site is not None:
                    return site
                scope = self.fns.get(scope.parent) if scope.parent else None
            site = self.bindings.get((fn.module, name))
            if site is not None:
                return site
            mod = pc.modules.get(fn.module)
            target = mod.imports.get(name) if mod else None
            if target:
                owner, _, leaf = target.rpartition(".")
                return self.bindings.get((owner, leaf))
            return None
        if ref[0] == "self" and fn.cls:
            ci = pc.classes.get(fn.cls)
            for c in (pc.mro_classes(ci) if ci else []):
                site = self.bindings.get(("cls", c.qual, ref[1]))
                if site is not None:
                    return site
            return None
        if ref[0] == "attr":
            mod = pc.modules.get(fn.module)
            target = self._scope_import(fn, ref[1]) or (
                mod.imports.get(ref[1]) if mod else None)
            if target:
                return self.bindings.get((target, ref[2]))
        return None

    def is_device_call(self, fn: FnRec, rec: CallRec) -> bool:
        """True when the call dispatches device work: a jitted binding, a
        tainted dispatching name/param, or a dispatching function."""
        # Monotone memo: the taint sets consulted below only ever grow, so
        # a True verdict stays True across fixpoint rounds.  Negatives are
        # memoized only when nothing dynamic could flip them: an
        # unresolvable ref, or a non-name ref whose (static) resolution
        # found no callee to ever join ``dispatching``.
        key = id(rec)
        if key in self._device_true:
            return True
        if key in self._device_false:
            return False
        hit = self._is_device_call(fn, rec)
        if hit:
            self._device_true.add(key)
        else:
            ref = rec.ref
            if ref is None or (ref[0] != "name"
                               and self.resolve_callee(fn, ref) is None):
                self._device_false.add(key)
        return hit

    def _is_device_call(self, fn: FnRec, rec: CallRec) -> bool:
        if self.site_for_call(fn, rec) is not None:
            return True
        ref = rec.ref
        if ref is None:
            return False
        if ref[0] == "name":
            name = ref[1]
            scope = fn
            while scope is not None:
                if name in self.dispatch_names.get(scope.qual, ()):
                    return True
                if name in self.param_taint.get(scope.qual, ()):
                    return True
                scope = self.fns.get(scope.parent) if scope.parent else None
        callee = self.resolve_callee(fn, ref)
        return callee is not None and callee in self.dispatching


# -- per-file scope extraction ------------------------------------------------

_FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


#: Node classes the scope walker handles specially; everything else recurses.
_SCOPE_NODES = frozenset({
    ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Call,
    ast.Assign, ast.AugAssign, ast.AnnAssign, ast.NamedExpr,
    ast.For, ast.AsyncFor, ast.While, ast.Return,
    ast.Global, ast.Nonlocal, ast.Import, ast.ImportFrom,
    ast.withitem, ast.comprehension,
})

#: Childless (or child-irrelevant) nodes: recursing into them only visits
#: ctx/operator tokens.
_LEAF_NODES = frozenset({
    ast.Name, ast.Constant, ast.Pass, ast.Break, ast.Continue,
    ast.Load, ast.Store, ast.Del, ast.alias,
})

#: Leaves plus the grammar-token singletons: visiting any of these is a
#: guaranteed no-op, so ``_children`` skips the dispatch call entirely --
#: they are the majority of all child visits.
_SKIP_NODES = _LEAF_NODES | _TOKEN_NODES


class _ScopeWalker:
    """Fill one FnRec from its body, stopping at nested function scopes
    (they get their own FnRec; call facts must not leak across -- same
    deferred-execution rule as project._BodyWalker)."""

    def __init__(self, rec: FnRec, register_nested):
        self.rec = rec
        self.register_nested = register_nested

    def _flat_targets(self, target: ast.expr) -> List:
        out: List = []
        stack = [target]
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)
            elif isinstance(t, ast.Name):
                out.append(t.id)
            elif (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.append(("self", t.attr))
        return out

    def _callee_ref(self, call: ast.Call) -> Optional[tuple]:
        f = call.func
        if isinstance(f, ast.Name):
            return ("name", f.id)
        if isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name):
                if recv.id == "self":
                    return ("self", f.attr)
                return ("attr", recv.id, f.attr)
            if (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)):
                if recv.value.id == "self":
                    return ("selfattr", recv.attr, f.attr)
                full = _dotted(f)
                if full is not None:
                    return ("dotted", full)
        return None

    def walk(self, body) -> None:
        for stmt in body:
            self.visit(stmt, (), ())

    def _children(self, node: ast.AST, loops: tuple, targets: tuple) -> None:
        # Inlined iter_child_nodes: two generator resumptions per node add
        # up over ~150k visits (same trick as findings._build_walk).
        visit = self.visit
        skip = _SKIP_NODES
        d = node.__dict__
        for name in node._fields:
            v = d.get(name)
            if v.__class__ is list:
                for item in v:
                    if item.__class__ not in skip \
                            and isinstance(item, ast.AST):
                        visit(item, loops, targets)
            elif v.__class__ not in skip and isinstance(v, ast.AST):
                visit(v, loops, targets)

    def visit(self, node: ast.AST, loops: tuple, targets: tuple) -> None:
        cls = node.__class__
        # Fast path: the vast majority of nodes are plain expressions with
        # no scope-relevant structure -- recurse (or stop, for leaves)
        # without running the dispatch chain below.
        if cls not in _SCOPE_NODES:
            if cls in _LEAF_NODES:
                return
            self._children(node, loops,
                           targets if cls is ast.Expr else ())
            return
        rec = self.rec
        if cls in (ast.FunctionDef, ast.AsyncFunctionDef):
            rec.local_names.add(node.name)
            self.register_nested(node, rec)
            return
        if cls is ast.Lambda:
            self.register_nested(node, rec)
            return
        if cls is ast.Call:
            rec.calls.append(CallRec(node, self._callee_ref(node),
                                     targets=targets, loop_stack=loops))
            self._children(node, loops, ())
            return
        if cls is ast.Assign:
            tgts = []
            for t in node.targets:
                tgts.extend(self._flat_targets(t))
            rec.local_names.update(t for t in tgts if isinstance(t, str))
            # ``profiler = StepProfiler(...)``: a local object whose method
            # calls resolve through the class (same heuristic as
            # project.attr_ctors -- capitalized callee name).
            if (len(tgts) == 1 and isinstance(tgts[0], str)
                    and isinstance(node.value, ast.Call)):
                cname = _base_name(node.value.func)
                if cname and cname[:1].isupper():
                    rec.local_ctors[tgts[0]] = cname
            self.visit(node.value, loops, tuple(tgts))
            return
        if cls is ast.AugAssign or cls is ast.AnnAssign:
            tgts = self._flat_targets(node.target)
            rec.local_names.update(t for t in tgts if isinstance(t, str))
            if node.value is not None:
                self.visit(node.value, loops, tuple(tgts))
            return
        if cls is ast.NamedExpr:
            tgts = self._flat_targets(node.target)
            rec.local_names.update(t for t in tgts if isinstance(t, str))
            self.visit(node.value, loops, tuple(tgts))
            return
        if cls is ast.For or cls is ast.AsyncFor:
            rec.local_names.update(
                t for t in self._flat_targets(node.target)
                if isinstance(t, str))
            rec.loops.append(node)
            inner = loops + (node,)
            self.visit(node.iter, loops, ())
            for stmt in node.body:
                self.visit(stmt, inner, ())
            for stmt in node.orelse:
                self.visit(stmt, loops, ())
            return
        if cls is ast.While:
            rec.loops.append(node)
            inner = loops + (node,)
            self.visit(node.test, inner, ())
            for stmt in node.body:
                self.visit(stmt, inner, ())
            for stmt in node.orelse:
                self.visit(stmt, loops, ())
            return
        if cls is ast.Return:
            if node.value is not None:
                rec.return_name_refs.update(
                    n.id for n in walk_fast(node.value)
                    if isinstance(n, ast.Name))
            if isinstance(node.value, ast.Name):
                rec.returns_names.add(node.value.id)
            elif node.value is not None:
                self.visit(node.value, loops, ())
            return
        if cls is ast.Global or cls is ast.Nonlocal:
            rec.outer_decls.update(node.names)
            return
        if cls is ast.Import:
            for alias in node.names:
                key = alias.asname or alias.name.split(".")[0]
                rec.imports[key] = alias.name
                rec.local_names.add(key)
            return
        if cls is ast.ImportFrom:
            base = node.module or ""
            if node.level:
                prefix = rec.module.split(".")[:-node.level]
                base = ".".join(prefix + ([base] if base else []))
            for alias in node.names:
                key = alias.asname or alias.name
                rec.imports[key] = f"{base}.{alias.name}" if base \
                    else alias.name
                rec.local_names.add(key)
            return
        if cls is ast.withitem:
            if node.optional_vars is not None:
                rec.local_names.update(
                    t for t in self._flat_targets(node.optional_vars)
                    if isinstance(t, str))
            self.visit(node.context_expr, loops, ())
            return
        if cls is ast.comprehension:
            rec.local_names.update(
                t for t in self._flat_targets(node.target)
                if isinstance(t, str))
        self._children(node, loops, targets if cls is ast.Expr else ())


# -- boundary construction ----------------------------------------------------

def boundary(pc: ProjectContext) -> Boundary:
    """The jit boundary for this run, built once and memoized on ``pc``."""
    cached = getattr(pc, "_jit_boundary", None)
    if cached is not None:
        return cached
    global BUILD_COUNT
    BUILD_COUNT += 1
    b = _build(pc)
    pc._jit_boundary = b
    return b


def _build(pc: ProjectContext) -> Boundary:
    b = Boundary(_pc=pc)
    builder = _Builder(pc, b)
    builder.collect_scopes()
    builder.collect_sites()
    builder.dispatch_fixpoint()
    builder.hot_map()
    builder.traced_closure()
    builder.taint_device_values()
    return b


class _Builder:
    def __init__(self, pc: ProjectContext, b: Boundary):
        self.pc = pc
        self.b = b
        #: ast node id -> FnRec (for site entry resolution).
        self.by_node: Dict[int, FnRec] = {}

    # -- scopes ---------------------------------------------------------------

    def collect_scopes(self) -> None:
        for rel, ctx in self.pc.files.items():
            if ctx.tree is None:
                continue
            # Test directories are outside the runtime dispatch graph the
            # boundary models, and every TJA020-023 consumer exempts them
            # anyway -- indexing their scopes is ~30% pure overhead.
            if is_test_path(rel):
                continue
            mod = self.pc.module_of_path(rel)
            if mod is None:
                continue
            cls_by_node = {id(ci.node): ci.qual
                           for ci in mod.classes.values()}
            # Top-level functions + methods seed the scope worklist; nested
            # defs/lambdas are registered by their enclosing _ScopeWalker.
            for name, node in mod.functions.items():
                self._add_scope(node, f"{mod.name}.{name}", rel, mod.name,
                                cls=None, parent=None)
            for ci in mod.classes.values():
                for name, node in ci.methods.items():
                    self._add_scope(node, f"{ci.qual}.{name}", rel,
                                    mod.name, cls=ci.qual, parent=None)
            # Module top-level statements form an implicit scope so module-
            # level jit bindings and loops are visible too.
            self._add_module_scope(ctx, mod, cls_by_node)

    def _add_module_scope(self, ctx: FileContext, mod, cls_by_node) -> None:
        qual = f"{mod.name}.<module>"
        rec = FnRec(qual=qual, node=ctx.tree, path=ctx.path,
                    module=mod.name)
        self.b.fns[qual] = rec
        self.by_node[id(ctx.tree)] = rec
        walker = _ScopeWalker(rec, self._register_nested)
        body = [stmt for stmt in ctx.tree.body
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))]
        walker.walk(body)

    def _add_scope(self, node: ast.AST, qual: str, path: str, module: str,
                   cls: Optional[str], parent: Optional[str]) -> FnRec:
        rec = FnRec(qual=qual, node=node, path=path, module=module,
                    cls=cls, parent=parent)
        a = node.args
        rec.params = [p.arg for p in a.posonlyargs + a.args]
        rec.params += [p.arg for p in a.kwonlyargs]
        if a.vararg:
            rec.params.append(a.vararg.arg)
        if a.kwarg:
            rec.params.append(a.kwarg.arg)
        rec.local_names.update(rec.params)
        # Annotated params type their receiver: ``service: DecodeService``
        # makes ``service.step()`` resolvable (string annotations too).
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            ann = getattr(p, "annotation", None)
            cname = None
            if isinstance(ann, (ast.Name, ast.Attribute)):
                cname = _base_name(ann)
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                cname = ann.value.split(".")[-1].strip()
            if cname and cname[:1].isupper():
                rec.local_ctors.setdefault(p.arg, cname)
        self.b.fns[qual] = rec
        self.by_node[id(node)] = rec
        walker = _ScopeWalker(rec, self._register_nested)
        if isinstance(node, ast.Lambda):
            walker.visit(node.body, (), ())
        else:
            walker.walk(node.body)
        return rec

    def _register_nested(self, node: ast.AST, parent: FnRec) -> None:
        if isinstance(node, ast.Lambda):
            qual = f"{parent.qual}.<lambda>L{node.lineno}"
        else:
            qual = f"{parent.qual}.<locals>.{node.name}"
        rec = self._add_scope(node, qual, parent.path, parent.module,
                              cls=parent.cls, parent=parent.qual)
        parent.nested.append(qual)
        # ``return inner`` / ``return lambda ...`` tracking: a Return whose
        # value IS the nested node is recorded via the parents map.
        anc = self._file_parents(parent.path).get(id(node))
        while anc is not None and not isinstance(anc, _FUNC_TYPES):
            if isinstance(anc, ast.Return):
                parent.returns_nested.add(qual)
                break
            anc = self._file_parents(parent.path).get(id(anc))

    def _file_parents(self, rel: str) -> dict:
        ctx = self.pc.files.get(rel)
        return ctx.parents if ctx is not None else {}

    # -- sites ----------------------------------------------------------------

    def collect_sites(self) -> None:
        for qual, rec in list(self.b.fns.items()):
            for cr in rec.calls:
                self._maybe_site(rec, cr)
            node = rec.node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._maybe_decorator_site(rec, node)

    def _tracing_kind(self, call: ast.Call) -> Optional[str]:
        name = _base_name(call.func)
        if name in TRACING_WRAPPERS:
            return name
        return None

    def _maybe_site(self, rec: FnRec, cr: CallRec) -> None:
        call = cr.node
        name = _base_name(call.func)
        if name in TRACING_WRAPPERS:
            site = self._make_site(rec, call, name, call, cr)
            entry = call.args[0] if call.args else None
            site.entry_qual = self._resolve_entry(rec, entry)
            self._bind(rec, cr, site)
            self.b.sites.append(site)
        elif name == "scan":
            # jax.lax.scan(body, ...): the body is traced even outside jit.
            dotted = _dotted(call.func) or ""
            if not (dotted.endswith("lax.scan") or dotted == "scan"):
                return
            site = self._make_site(rec, call, "scan", None, cr)
            entry = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "f":
                    entry = kw.value
            site.entry_qual = self._resolve_entry(rec, entry)
            self.b.sites.append(site)

    def _make_site(self, rec: FnRec, call: ast.Call, kind: str,
                   statics_src: Optional[ast.Call],
                   cr: Optional[CallRec] = None) -> JitSite:
        site = JitSite(path=rec.path, line=call.lineno,
                       col=call.col_offset, kind=kind,
                       owner_qual=rec.qual,
                       wrap_in_loop=bool(cr and cr.loop_stack))
        if statics_src is not None:
            self._fill_argnums(site, statics_src)
        return site

    def _fill_argnums(self, site: JitSite, call: ast.Call) -> None:
        nums, has = _int_tuple(call, "static_argnums")
        site.static_argnums, site.has_static = nums, has
        names, has = _str_tuple(call, "static_argnames")
        site.static_argnames = names
        site.has_static = site.has_static or has
        nums, has = _int_tuple(call, "donate_argnums")
        site.donate_argnums, site.has_donate = nums, has
        names, has = _str_tuple(call, "donate_argnames")
        site.donate_argnames = names
        site.has_donate = site.has_donate or has

    def _maybe_decorator_site(self, rec: FnRec, node) -> None:
        for dec in node.decorator_list:
            wrap = None
            if isinstance(dec, ast.Call):
                name = _base_name(dec.func)
                if name in TRACING_WRAPPERS:
                    wrap = dec
                elif name == "partial" and dec.args \
                        and _base_name(dec.args[0]) in TRACING_WRAPPERS:
                    wrap = dec
            elif _base_name(dec) in TRACING_WRAPPERS:
                wrap = ast.Call(func=dec, args=[], keywords=[])
                wrap.lineno, wrap.col_offset = dec.lineno, dec.col_offset
            if wrap is None:
                continue
            site = JitSite(path=rec.path, line=node.lineno,
                           col=node.col_offset, kind="decorator",
                           entry_qual=rec.qual,
                           owner_qual=rec.parent or f"{rec.module}.<module>")
            self._fill_argnums(site, wrap)
            self.b.sites.append(site)
            # The decorated NAME becomes a dispatching binding in its scope.
            if rec.parent:
                parent = self.b.fns[rec.parent]
                parent.jit_bindings.setdefault(node.name, site)
            elif rec.cls is None:
                self.b.bindings.setdefault((rec.module, node.name), site)
            else:
                self.b.bindings.setdefault(("cls", rec.cls, node.name), site)

    def _resolve_entry(self, rec: FnRec,
                       entry: Optional[ast.expr]) -> Optional[str]:
        """FnRec qual for the traced callable expression at a wrap site."""
        while isinstance(entry, ast.Call) \
                and _base_name(entry.func) == "partial" and entry.args:
            entry = entry.args[0]
        if entry is None:
            return None
        nested = self.by_node.get(id(entry))
        if nested is not None:           # jit(lambda ...: ...)
            return nested.qual
        if isinstance(entry, ast.Name):
            return self.b.resolve_callee(rec, ("name", entry.id))
        if isinstance(entry, ast.Attribute):
            full = _dotted(entry)
            if full and "." in full:
                head, _, restpath = full.partition(".")
                qual = self.b.resolve_callee(
                    rec, ("attr", head, restpath)) \
                    if "." not in restpath else None
                if qual:
                    return qual
                return self.b.resolve_callee(rec, ("dotted", full))
        return None

    def _bind(self, rec: FnRec, cr: CallRec, site: JitSite) -> None:
        """Record what name the jitted callable is bound to."""
        for t in cr.targets:
            if isinstance(t, str):
                if rec.node.__class__ is ast.Module:
                    self.b.bindings[(rec.module, t)] = site
                else:
                    rec.jit_bindings[t] = site
            elif isinstance(t, tuple) and t[0] == "self" and rec.cls:
                self.b.bindings[("cls", rec.cls, t[1])] = site

    # -- dispatch fixpoint ----------------------------------------------------

    def _settle_never_dispatch(self) -> None:
        """Pre-settle name-calls the fixpoint can never flip to device.

        A ``("name", n)`` call dispatches only if (a) it hits a jit binding
        (``site_for_call`` -- static once sites are collected), (b) ``n``
        lands in ``dispatch_names``/``param_taint`` of its scope chain, or
        (c) its resolved callee joins ``dispatching``.  ``dispatch_names``
        only ever receives *assignment targets of calls* in a scope and
        ``param_taint`` only that scope's *parameters*, so when ``n`` is
        neither anywhere on the chain and resolution is static-None, the
        verdict is False forever -- settle it now.  This covers the builtin
        /stdlib calls (len, sorted, print, ...) that otherwise dominate
        every fixpoint round's re-check."""
        b = self.b
        possible: Dict[str, Set[str]] = {}

        def chain_names(qual: str) -> Set[str]:
            got = possible.get(qual)
            if got is None:
                rec = b.fns[qual]
                got = set(rec.params)
                for cr in rec.calls:
                    for t in cr.targets:
                        if isinstance(t, str):
                            got.add(t)
                if rec.parent and rec.parent in b.fns:
                    got |= chain_names(rec.parent)
                possible[qual] = got
            return got

        for qual, rec in b.fns.items():
            for cr in rec.calls:
                ref = cr.ref
                if ref is None or ref[0] != "name":
                    continue
                cid = id(cr)
                if cid in b._device_true or cid in b._device_false:
                    continue
                if b.site_for_call(rec, cr) is not None:
                    continue
                if b.resolve_callee(rec, ref) is not None:
                    continue
                if ref[1] not in chain_names(qual):
                    b._device_false.add(cid)

    def dispatch_fixpoint(self) -> None:
        b = self.b
        self._settle_never_dispatch()
        changed = True
        rounds = 0
        while changed and rounds < 20:
            changed = False
            rounds += 1
            for qual, rec in b.fns.items():
                disp = qual in b.dispatching
                ret = qual in b.returns_dispatch
                names = b.dispatch_names.setdefault(qual, set())
                for cr in rec.calls:
                    # Settled calls contribute nothing new: a proven
                    # dispatch already marked its owner, and a static
                    # never-dispatch has no callee to propagate through.
                    cid = id(cr)
                    if cid in b._device_true or cid in b._device_false:
                        continue
                    if b.is_device_call(rec, cr):
                        if not disp:
                            b.dispatching.add(qual)
                            disp = changed = True
                        callee = None
                    else:
                        callee = b.resolve_callee(rec, cr.ref)
                        if callee in b.dispatching and not disp:
                            b.dispatching.add(qual)
                            disp = changed = True
                    if callee and callee in b.returns_dispatch:
                        for t in cr.targets:
                            if isinstance(t, str) and t not in names:
                                names.add(t)
                                changed = True
                    # Argument taint: passing a dispatching callable into a
                    # known function taints that parameter.
                    if callee:
                        changed |= self._taint_args(rec, cr, callee)
                # Returns.
                if not ret:
                    retnames = rec.returns_names
                    if (retnames & names
                            or retnames & rec.jit_bindings.keys()
                            or retnames & b.param_taint.get(qual, set())
                            or any(n in b.dispatching
                                   for n in rec.returns_nested)):
                        b.returns_dispatch.add(qual)
                        changed = True

    def _is_dispatching_arg(self, rec: FnRec, arg: ast.expr) -> bool:
        b = self.b
        if isinstance(arg, ast.Name):
            name = arg.id
            scope = rec
            while scope is not None:
                if (name in scope.jit_bindings
                        or name in b.dispatch_names.get(scope.qual, ())
                        or name in b.param_taint.get(scope.qual, ())):
                    return True
                nested = f"{scope.qual}.<locals>.{name}"
                if nested in b.dispatching:
                    return True
                scope = b.fns.get(scope.parent) if scope.parent else None
            if (rec.module, name) in b.bindings:
                return True
            return f"{rec.module}.{name}" in b.dispatching
        if (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)):
            if arg.value.id == "self" and rec.cls:
                return ("cls", rec.cls, arg.attr) in b.bindings
        return False

    def _taint_args(self, rec: FnRec, cr: CallRec, callee: str) -> bool:
        target = self.b.fns.get(callee)
        if target is None or not target.params:
            return False
        changed = False
        taint = self.b.param_taint.setdefault(callee, set())
        params = target.params
        offset = 1 if (target.cls and params and params[0] == "self") else 0
        for i, arg in enumerate(cr.node.args):
            if self._is_dispatching_arg(rec, arg):
                idx = i + offset
                if idx < len(params) and params[idx] not in taint:
                    taint.add(params[idx])
                    changed = True
        for kw in cr.node.keywords:
            if kw.arg and self._is_dispatching_arg(rec, kw.value):
                if kw.arg in params and kw.arg not in taint:
                    taint.add(kw.arg)
                    changed = True
        return changed

    # -- hot-loop map ---------------------------------------------------------

    def _round_trip(self, rec: FnRec, calls: List[CallRec]):
        """Loop-carried device values among ``calls``: targets of device
        calls that feed back into device-call arguments."""
        b = self.b
        produced: Set = set()
        consumed: Set = set()
        for cr in calls:
            if not b.is_device_call(rec, cr):
                continue
            produced.update(cr.targets)
            # walk_fast: memoized on the Call node -- every loop pass over
            # a scope re-walks the same device-call expressions.
            for arg in walk_fast(cr.node):
                if isinstance(arg, ast.Name):
                    consumed.add(arg.id)
                elif (isinstance(arg, ast.Attribute)
                        and isinstance(arg.value, ast.Name)
                        and arg.value.id == "self"):
                    consumed.add(("self", arg.attr))
        carried = produced & consumed
        return tuple(sorted(t if isinstance(t, str) else f"self.{t[1]}"
                            for t in carried))

    def hot_map(self) -> None:
        b = self.b
        # Tick functions: a device-call round trip anywhere in the body.
        ticks: Dict[str, Tuple[str, ...]] = {}
        for qual, rec in b.fns.items():
            carried = self._round_trip(rec, rec.calls)
            if carried:
                ticks[qual] = carried
        # leads-to-tick: calling it (transitively) runs a tick round trip.
        leads: Dict[str, str] = {q: q for q in ticks}
        changed = True
        rounds = 0
        while changed and rounds < 20:
            changed = False
            rounds += 1
            for qual, rec in b.fns.items():
                if qual in leads:
                    continue
                for cr in rec.calls:
                    callee = b.resolve_callee(rec, cr.ref)
                    if callee in leads:
                        leads[qual] = leads[callee]
                        changed = True
                        break
        # Hot loops: carried round trip lexically inside the loop, or a
        # call into a tick chain per iteration.
        for qual, rec in b.fns.items():
            for loop in rec.loops:
                in_loop = [cr for cr in rec.calls
                           if loop in cr.loop_stack]
                carried = self._round_trip(rec, in_loop)
                via = ""
                if not carried:
                    for cr in in_loop:
                        callee = b.resolve_callee(rec, cr.ref)
                        if callee in leads:
                            tick = leads[callee]
                            carried = ticks[tick]
                            via = f"via {tick.rsplit('.', 1)[-1]}()"
                            break
                if carried:
                    b.hot_loops.append(HotLoop(
                        path=rec.path, line=loop.lineno, fn_qual=qual,
                        carried=carried, via=via))
        # Functions reachable from hot-loop bodies run once per iteration.
        work: List[Tuple[str, HotLoop]] = []
        for hl in b.hot_loops:
            rec = b.fns[hl.fn_qual]
            for cr in rec.calls:
                if any(lp.lineno == hl.line for lp in cr.loop_stack):
                    callee = b.resolve_callee(rec, cr.ref)
                    if callee and callee not in b.hot_fns:
                        b.hot_fns[callee] = hl
                        work.append((callee, hl))
        while work:
            qual, hl = work.pop()
            rec = b.fns.get(qual)
            if rec is None:
                continue
            for cr in rec.calls:
                callee = b.resolve_callee(rec, cr.ref)
                if callee and callee not in b.hot_fns:
                    b.hot_fns[callee] = hl
                    work.append((callee, hl))

    # -- traced closure -------------------------------------------------------

    def traced_closure(self) -> None:
        b = self.b
        work: List[Tuple[str, JitSite]] = []
        for site in b.sites:
            if site.entry_qual and site.entry_qual in b.fns:
                work.append((site.entry_qual, site))
        while work:
            qual, site = work.pop()
            sites = b.closure.setdefault(qual, [])
            if site in sites:
                continue
            sites.append(site)
            rec = b.fns.get(qual)
            if rec is None:
                continue
            for cr in rec.calls:
                callee = b.resolve_callee(rec, cr.ref)
                if callee and callee in b.fns:
                    if site not in b.closure.get(callee, ()):
                        work.append((callee, site))
            # Nested defs (scan bodies, layer closures) trace with their
            # parent -- they run inside the same staged computation.
            for nested in rec.nested:
                if site not in b.closure.get(nested, ()):
                    work.append((nested, site))

    # -- device-value taint (hot scope) ---------------------------------------

    def taint_device_values(self) -> None:
        """Names holding device values, per hot-scope function: targets of
        device calls, plus params fed device values from hot call sites."""
        b = self.b
        hot_quals = set(b.hot_fns) | {hl.fn_qual for hl in b.hot_loops}
        for qual in hot_quals:
            rec = b.fns.get(qual)
            if rec is None:
                continue
            taint = b.device_taint.setdefault(qual, set())
            for cr in rec.calls:
                if b.is_device_call(rec, cr):
                    taint.update(cr.targets)
        # A few propagation rounds: hot call sites passing tainted names
        # taint the callee's parameters (the profiler-fence shape), and a
        # callee returning tainted names taints the caller's assignment
        # targets (``params, opt, loss, _ = run_elastic_loop(...)``).
        for _ in range(4):
            changed = False
            for qual in hot_quals:
                rec = b.fns.get(qual)
                if rec is None:
                    continue
                taint = b.device_taint.get(qual, set())
                for cr in rec.calls:
                    callee = b.resolve_callee(rec, cr.ref)
                    if not callee or callee not in hot_quals:
                        continue
                    target = b.fns[callee]
                    ctaint = b.device_taint.setdefault(callee, set())
                    params = target.params
                    offset = 1 if (target.cls and params
                                   and params[0] == "self") else 0
                    for i, arg in enumerate(cr.node.args):
                        if self._arg_tainted(rec, taint, arg):
                            idx = i + offset
                            if idx < len(params) \
                                    and params[idx] not in ctaint:
                                ctaint.add(params[idx])
                                changed = True
                    for kw in cr.node.keywords:
                        if kw.arg and kw.arg in params \
                                and self._arg_tainted(rec, taint, kw.value) \
                                and kw.arg not in ctaint:
                            ctaint.add(kw.arg)
                            changed = True
                    # Return taint: callee returns device values -> the
                    # call's targets hold device values here.
                    if target.return_name_refs & ctaint:
                        for t in cr.targets:
                            if t not in taint:
                                b.device_taint.setdefault(
                                    qual, taint).add(t)
                                taint = b.device_taint[qual]
                                changed = True
            if not changed:
                break

    @staticmethod
    def _arg_tainted(rec: FnRec, taint: Set, arg: ast.expr) -> bool:
        if isinstance(arg, ast.Name):
            return arg.id in taint
        if (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == "self"):
            return ("self", arg.attr) in taint
        return False
