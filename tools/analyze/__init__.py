"""AST-based operator-lint suite (docs/STATIC_ANALYSIS.md).

Nineteen repo-specific passes over stdlib ``ast`` — twelve per-file, seven
whole-program (a ``ProjectContext`` built once per run over the shared
per-file trees); the TJA015+ passes are *path-sensitive*, running gen-kill
dataflow over lazily-built per-function CFGs (cfg.py, dataflow.py):

=======  ==============================  =======================================
ID       name                            what it catches
=======  ==============================  =======================================
TJA001   py-compat                       files that don't parse under the oldest
                                         supported grammar (Python 3.10)
TJA002   lock-discipline                 attribute mutations outside ``with
                                         self._lock:`` in lock-owning classes
TJA003   reconcile-purity                sleeps / blocking IO / unbounded waits
                                         inside controller reconcile paths
TJA004   broad-except                    swallowed ``except Exception:`` without
                                         log, re-raise, forward, or waiver
TJA005   constant-drift                  contract strings inlined instead of
                                         taken from api/constants.py
TJA006   tracer-safety                   host syncs / Python control flow on
                                         traced values inside jit/pmap/shard_map
TJA007   event-reason-drift              recorder.event reasons outside the
                                         EVENT_REASONS registry
TJA008   orphaned-thread                 non-daemon threads with no join
TJA009   status-write-discipline         raw job.status writes outside the
                                         status machine's helpers
TJA010   lock-order-cycle                cycles in the global lock-acquisition-
                                         order graph (potential deadlocks)
TJA011   env-contract                    TRAININGJOB_* vars read-never-injected
                                         / injected-never-read / undeclared
TJA012   metric-name-drift               emitted Prometheus names vs the
                                         docs/OBSERVABILITY.md registry
TJA013   phase-transition-exhaustiveness update_job_conditions call sites vs
                                         the PHASE_TRANSITIONS legal table
TJA014   dead-event-reason               EVENT_REASONS members nothing uses
TJA015   resource-leak                   sockets/files/processes acquired but
                                         not released on some exit path
TJA016   lock-held-blocking-call         blocking I/O reachable while a lock
                                         is held (transitive + path-sensitive)
TJA017   exception-escape                thread targets an uncaught exception
                                         can kill silently
TJA018   retry-without-backoff           while-retry loops re-entering remote
                                         I/O with no pause on the back edge
TJA019   finally-state-restore           toggles restored on the normal path
                                         but not the exception path
=======  ==============================  =======================================

Run: ``python -m tools.analyze trainingjob_operator_tpu/`` (see __main__.py).
"""

from tools.analyze.findings import ERROR, WARNING, FileContext, Finding
from tools.analyze.runner import (
    REGISTRY,
    apply_baseline,
    format_findings,
    load_baseline,
    run_checks,
    write_baseline,
)

__all__ = [
    "ERROR", "WARNING", "FileContext", "Finding", "REGISTRY",
    "apply_baseline", "format_findings", "load_baseline", "run_checks",
    "write_baseline",
]
