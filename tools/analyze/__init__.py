"""AST-based operator-lint suite (docs/STATIC_ANALYSIS.md).

Six repo-specific passes over stdlib ``ast``:

=======  =================  =====================================================
ID       name               what it catches
=======  =================  =====================================================
TJA001   py-compat          files that don't parse under the oldest supported
                            grammar (Python 3.10), e.g. f-string backslashes
TJA002   lock-discipline    attribute mutations outside ``with self._lock:`` in
                            classes that create a Lock/RLock/Condition
TJA003   reconcile-purity   time.sleep / blocking HTTP-socket calls / unbounded
                            waits inside controller reconcile paths
TJA004   broad-except       ``except Exception:`` / bare ``except:`` that neither
                            logs, re-raises, nor carries a waiver comment
TJA005   constant-drift     label/annotation/env-var contract strings used inline
                            instead of via api/constants.py
TJA006   tracer-safety      Python control flow on traced values, float()/.item()
                            host syncs, and print() inside jit/pmap/shard_map
=======  =================  =====================================================

Run: ``python -m tools.analyze trainingjob_operator_tpu/`` (see __main__.py).
"""

from tools.analyze.findings import ERROR, WARNING, FileContext, Finding
from tools.analyze.runner import (
    REGISTRY,
    apply_baseline,
    format_findings,
    load_baseline,
    run_checks,
    write_baseline,
)

__all__ = [
    "ERROR", "WARNING", "FileContext", "Finding", "REGISTRY",
    "apply_baseline", "format_findings", "load_baseline", "run_checks",
    "write_baseline",
]
