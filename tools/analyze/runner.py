"""Check registry, file walking, baseline handling, and output formats.

The analyzer is a sub-second pre-test gate (docs/STATIC_ANALYSIS.md): every
pass works off one shared ``ast`` parse -- and one shared ``ast.walk``
(``FileContext.nodes``/``by_type``) -- per file, and the whole-program
``ProjectContext`` is built once per run, so the package is analyzed in well
under a second (``make lint`` asserts < 2 s repo-wide via ``--max-seconds``)
-- cheap enough to run before every pytest invocation via
tests/test_static_analysis.py and ``make lint``.

Baseline protocol: ``--write-baseline`` snapshots the current findings as
grandfathered; subsequent runs report only *new* findings (and exit 0 when
there are none).  Fingerprints are line-number independent (findings.py) so
edits elsewhere in a file don't invalidate the baseline.
"""

from __future__ import annotations

import ast
import gc
import json
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from tools.analyze.findings import ERROR, FileContext, Finding, fingerprint_all
from tools.analyze.project import ProjectContext

#: check_name -> (check_id, run callable).  Populated by @register.
REGISTRY: Dict[str, Tuple[str, Callable[[FileContext], List[Finding]]]] = {}

#: Whole-program passes: check_name -> (check_id, fn(ProjectContext)).
#: These run once per invocation, after every file is parsed, against the
#: shared ProjectContext (symbol table + import/call/lock graphs).
PROJECT_REGISTRY: Dict[str, Tuple[str, Callable[[ProjectContext],
                                                List[Finding]]]] = {}

#: Directories never analyzed (vendored/output trees).
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
             ".eggs", "node_modules"}

#: Default baseline location, loaded when --baseline is not given.
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def register(check_id: str, check_name: str):
    """Decorator: install ``fn(FileContext) -> List[Finding]`` in REGISTRY."""
    def wrap(fn):
        REGISTRY[check_name] = (check_id, fn)
        fn.check_id, fn.check_name = check_id, check_name
        return fn
    return wrap


def register_project(check_id: str, check_name: str):
    """Decorator: install ``fn(ProjectContext) -> List[Finding]`` in
    PROJECT_REGISTRY (whole-program, runs once per invocation)."""
    def wrap(fn):
        PROJECT_REGISTRY[check_name] = (check_id, fn)
        fn.check_id, fn.check_name = check_id, check_name
        return fn
    return wrap


def _load_checks() -> None:
    # Import for side effect: each module @register's its pass.
    from tools.analyze.checks import (  # noqa: F401
        broad_except, check_then_act, constant_drift, dead_reasons,
        digest_stability, donation_discipline, env_contract, event_reasons,
        exception_escape, finally_restore, host_sync_hot_loop, impure_capture,
        iteration_order, lock_blocking, lock_discipline, lock_order,
        metric_drift, orphaned_thread, phase_transitions, py_compat,
        recompile_hazard, reconcile_purity, resource_leak, retry_backoff,
        shard_boundary, shard_state, shutdown_ordering, status_discipline,
        tracer_safety, unguarded_shared_state, unseeded_randomness,
        wait_discipline,
    )


def all_checks() -> Dict[str, str]:
    """check_id -> check_name across both registries (loads them)."""
    _load_checks()
    out = {cid: name for name, (cid, _fn) in REGISTRY.items()}
    out.update({cid: name for name, (cid, _fn) in PROJECT_REGISTRY.items()})
    return out


def iter_py_files(paths: Iterable[str], root: str) -> List[str]:
    out: List[str] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            out.append(ap)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def make_context(abs_path: str, root: str) -> FileContext:
    with open(abs_path, "r", encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    rel = os.path.relpath(abs_path, root).replace(os.sep, "/")
    ctx = FileContext(path=rel, abs_path=abs_path, source=source,
                      lines=source.splitlines())
    try:
        ctx.tree = ast.parse(source, filename=rel)
    except SyntaxError:
        ctx.tree = None  # py_compat reports it; other passes skip the file
    return ctx


def run_checks(paths: Iterable[str], root: Optional[str] = None,
               only: Optional[Iterable[str]] = None,
               report_only: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run every registered pass (or the ``only`` subset, by name or id)
    over the .py files under ``paths``.  Waived findings are dropped here so
    every pass gets the same waiver semantics for free.

    ``report_only`` (repo-relative paths) is incremental mode: file passes
    run only on those files, and project passes -- which still build the
    whole-program context from every file under ``paths``, since the call
    graph spans unchanged code -- report only findings landing in them.

    The cyclic GC is suspended for the duration of the run: analysis
    allocates millions of AST nodes plus the walk/bucket/CFG caches over
    them, and the resulting full-generation collections were the single
    largest slice of the ``make lint`` --max-seconds budget (~30% of
    wall-clock).  Reference counting still reclaims everything acyclic;
    the process is short-lived either way."""
    was_enabled = gc.isenabled()
    gc.disable()
    try:
        return _run_checks(paths, root, only, report_only)
    finally:
        if was_enabled:
            gc.enable()


def _run_checks(paths: Iterable[str], root: Optional[str] = None,
                only: Optional[Iterable[str]] = None,
                report_only: Optional[Iterable[str]] = None) -> List[Finding]:
    _load_checks()
    root = root or os.getcwd()
    selected = REGISTRY
    selected_project = PROJECT_REGISTRY
    if only:
        wanted = set(only)

        def pick(registry):
            return {name: pair for name, pair in registry.items()
                    if name in wanted or pair[0] in wanted}

        selected, selected_project = pick(REGISTRY), pick(PROJECT_REGISTRY)
        matched = set(selected) | set(selected_project) \
            | {pair[0] for pair in selected.values()} \
            | {pair[0] for pair in selected_project.values()}
        unknown = wanted - matched
        if unknown:
            raise ValueError(
                f"unknown check(s): {sorted(unknown)}; "
                f"known: {sorted(REGISTRY) + sorted(PROJECT_REGISTRY)}")
    wanted_paths = set(report_only) if report_only is not None else None
    findings: List[Finding] = []
    contexts: Dict[str, FileContext] = {}
    for abs_path in iter_py_files(paths, root):
        ctx = make_context(abs_path, root)
        contexts[ctx.path] = ctx
        if wanted_paths is not None and ctx.path not in wanted_paths:
            continue
        for name, (_cid, fn) in selected.items():
            for f in fn(ctx):
                if not ctx.waived(f.line, name):
                    findings.append(f)
    if selected_project:
        # One shared whole-program context for every interprocedural pass,
        # built from the per-file ASTs parsed above (no re-parse).
        project = ProjectContext.build(root, contexts)
        for name, (_cid, fn) in selected_project.items():
            for f in fn(project):
                if wanted_paths is not None and f.path not in wanted_paths:
                    continue
                fctx = contexts.get(f.path)
                if fctx is None or not fctx.waived(f.line, name):
                    findings.append(f)
    findings.sort(key=Finding.sort_key)
    return findings


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, dict]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return data.get("findings", {})


def write_baseline(path: str, findings: List[Finding]) -> int:
    entries = {
        fp: {"check": f.check_id, "path": f.path, "message": f.message}
        for fp, f in fingerprint_all(findings).items()
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    return len(entries)


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, dict]) -> Tuple[List[Finding], int]:
    """Split into (new findings, count of grandfathered ones suppressed)."""
    fresh = [f for fp, f in fingerprint_all(findings).items()
             if fp not in baseline]
    fresh.sort(key=Finding.sort_key)
    return fresh, len(findings) - len(fresh)


# -- output ------------------------------------------------------------------

#: check_id -> one-line rule description, surfaced as the SARIF rule's
#: fullDescription (code-scanning UIs show it next to each alert).  The
#: full prose lives in docs/STATIC_ANALYSIS.md's catalog; tests assert
#: this map covers every registered check.
RULE_HELP: Dict[str, str] = {
    "TJA001": "Files must parse under the oldest supported grammar "
              "(Python 3.10); backslashes in f-string fields included.",
    "TJA002": "Attributes guarded by a lock in one method must be guarded "
              "everywhere (static race detector).",
    "TJA003": "Reconcile paths must not sleep, do raw I/O, or wait "
              "unbounded; return and re-enqueue instead.",
    "TJA004": "except Exception must re-raise, log, or forward the bound "
              "exception -- swallowing is a decision, not a default.",
    "TJA005": "Label/annotation/env-var contract strings must come from "
              "api/constants.py, not inline literals.",
    "TJA006": "No Python branches on traced values, host syncs, or prints "
              "inside jit/pmap/shard_map-wrapped functions.",
    "TJA007": "recorder.event(...) reasons must come from the "
              "EVENT_REASONS registry in api/constants.py.",
    "TJA008": "threading.Thread needs daemon=True or join evidence; a "
              "leaked non-daemon thread blocks shutdown.",
    "TJA009": "job.status.phase/conditions writes must go through the "
              "status machine's helpers, never raw assignment.",
    "TJA010": "Whole-program lock-acquisition-order graph must stay "
              "acyclic (deadlock detector).",
    "TJA011": "Every TRAININGJOB_* env var must be declared, injected, "
              "and read -- three-way contract consistency.",
    "TJA012": "Emitted trainingjob_* metric names must match the "
              "documented registry in docs/OBSERVABILITY.md.",
    "TJA013": "Witnessed phase transitions must be legal per "
              "PHASE_TRANSITIONS in api/constants.py.",
    "TJA014": "EVENT_REASONS members never emitted anywhere are dead "
              "documented events.",
    "TJA015": "Resources acquired from factories must be released on "
              "every CFG path (exception paths included).",
    "TJA016": "No blocking I/O reachable while a lock is held -- one "
              "congested peer stalls every contending thread.",
    "TJA017": "Thread targets must not let exceptions escape silently "
              "(whole-project escaping-type fixpoint).",
    "TJA018": "Remote-retry loops need a pause (with jitter in client/"
              "controller code) on the back edge.",
    "TJA019": "Sentinel flags toggled around blocking regions must be "
              "restored on exception paths (finally).",
    "TJA020": "No jit wrapper construction in loops and no cache-key-"
              "churning static arguments.",
    "TJA021": "No device-to-host syncs on hot-loop paths; deliberate "
              "fences carry documented waivers.",
    "TJA022": "Donated buffers must not be read after the donating call; "
              "hot state round trips should donate.",
    "TJA023": "No side effects on outside-owned state inside traced "
              "closures (they run at trace time, not per step).",
    "TJA024": "Determinism-scoped code must draw randomness only from "
              "explicitly seeded random.Random instances.",
    "TJA025": "Nondeterministic values (wall clock, id(), entropy, "
              "unsorted sets) must not reach digest sinks.",
    "TJA026": "Loops over sets with order-dependent side effects must "
              "iterate sorted(...).",
    "TJA027": "Module-level mutable singletons must be classified in "
              "SHARD_STATE_REGISTRY (shard-state inventory).",
    "TJA028": "State shared between may-happen-in-parallel threads with a "
              "write and disjoint lock-sets is a data race; guard both "
              "sites under one lock.",
    "TJA029": "A test of shared state and the conditional mutation it "
              "guards must be spanned by one lock (check-then-act race).",
    "TJA030": "Condition.wait must sit in a predicate loop; unbounded "
              "Event.wait/join inside a stoppable thread role parks it "
              "forever.",
    "TJA031": "Retained threads must be joined by their owner's stop path, "
              "and never under a lock the thread itself acquires.",
    "TJA032": "SHARD_STATE_REGISTRY classifications must hold against the "
              "thread model: lock_guarded access is locked, shard_local is "
              "not raced, globals rebound from threads are declared.",
}

#: check_id -> SARIF defaultConfiguration level.  Checks that emit both
#: severities default to their dominant (error) level; per-result levels
#: still carry the exact severity.
RULE_DEFAULT_LEVELS: Dict[str, str] = {
    "TJA004": "warning", "TJA018": "warning", "TJA019": "warning",
    "TJA021": "warning", "TJA030": "warning", "TJA031": "warning",
}


def format_sarif(findings: List[Finding]) -> str:
    """Minimal SARIF 2.1.0: one run, rules from the registry, results with
    a physical location + level -- enough for GitHub code-scanning upload,
    replacing the bespoke ``github`` annotation format in CI."""
    rules = [{
        "id": cid,
        "name": name,
        "shortDescription": {"text": name},
        "fullDescription": {"text": RULE_HELP.get(cid, name)},
        "helpUri": ("https://example.invalid/docs/STATIC_ANALYSIS.md"
                    "#check-catalog"),
        "defaultConfiguration": {
            "level": RULE_DEFAULT_LEVELS.get(cid, "error")},
    } for cid, name in sorted(all_checks().items())]
    results = [{
        "ruleId": f.check_id,
        "level": "error" if f.severity == ERROR else "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": f.line,
                           "startColumn": max(f.col, 1)},
            },
        }],
    } for f in findings]
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tools.analyze",
                "informationUri":
                    "https://example.invalid/docs/STATIC_ANALYSIS.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def format_findings(findings: List[Finding], fmt: str) -> str:
    if fmt == "sarif":
        return format_sarif(findings)
    if fmt == "json":
        return json.dumps([{
            "check_id": f.check_id, "check": f.check_name, "path": f.path,
            "line": f.line, "col": f.col, "severity": f.severity,
            "message": f.message,
        } for f in findings], indent=2) + "\n"
    if fmt == "github":
        # GitHub Actions workflow-command annotations.
        lines = []
        for f in findings:
            kind = "error" if f.severity == ERROR else "warning"
            lines.append(f"::{kind} file={f.path},line={f.line},"
                         f"col={f.col},title={f.check_id} {f.check_name}::"
                         f"{f.message}")
        return "\n".join(lines) + ("\n" if lines else "")
    # text
    lines = [f"{f.location()}: {f.check_id}[{f.check_name}] "
             f"{f.severity}: {f.message}" for f in findings]
    return "\n".join(lines) + ("\n" if lines else "")
