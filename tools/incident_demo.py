"""Run one simulated TrainingJob through a scripted preemption and print
the incident flight recorder's phase-attributed downtime table.

The ``make incident-demo`` driver: in-process sim cluster, one 2-replica
job with restart-on-exit-code semantics (scope ALL).  Once it is Running
and reporting steps, the demo kills a pod with exit 137 -- the controller
drains and restarts the whole gang, the flight recorder (obs/incident.py)
captures the window, and the first post-recovery step record amends the
bundle with the workload tail (the sim synthesizes the resume record a
real workload's ``overlapped_restore`` would push).  The demo prints the
per-phase downtime table -- the same bundle ``/debug/incidents?job=...``
serves -- and cross-checks the control window against the goodput ledger.

Usage::

    python -m tools.incident_demo [--timeout 30]
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("incident-demo")
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="Give up if no amended bundle by then.")
    args = parser.parse_args(argv)

    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.api.types import (
        ReplicaSpec,
        RestartPolicy,
        RestartScope,
        TPUTrainingJob,
    )
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import (
        TrainingJobController,
    )
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        ObjectMeta,
        PodPhase,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.obs.goodput import GOODPUT
    from trainingjob_operator_tpu.obs.incident import INCIDENTS, PHASES
    from trainingjob_operator_tpu.obs.telemetry import TELEMETRY
    from trainingjob_operator_tpu.runtime.sim import (
        CKPT_MS_ANNOTATION,
        COMPILE_MS_ANNOTATION,
        HBM_BYTES_ANNOTATION,
        RESTORE_MS_ANNOTATION,
        RUN_SECONDS_ANNOTATION,
        STEP_MS_ANNOTATION,
        TOKENS_PER_STEP_ANNOTATION,
        SimRuntime,
    )

    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.add_node("sim-0")
    sim.add_node("sim-1")
    sim.start()
    tc.run(workers=2)
    job_key = "default/incident-demo"
    try:
        job = TPUTrainingJob(metadata=ObjectMeta(name="incident-demo",
                                                 namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=2,
            restart_policy=RestartPolicy.EXIT_CODE,
            restart_scope=RestartScope.ALL,
            template=PodTemplateSpec(
                metadata=ObjectMeta(annotations={
                    RUN_SECONDS_ANNOTATION: str(args.timeout * 2),
                    STEP_MS_ANNOTATION: "20",
                    TOKENS_PER_STEP_ANNOTATION: "8192",
                    CKPT_MS_ANNOTATION: "1.5",
                    HBM_BYTES_ANNOTATION: "2.5e9",
                    RESTORE_MS_ANNOTATION: "120",
                    COMPILE_MS_ANNOTATION: "200",
                }),
                spec=PodSpec(containers=[
                    Container(name="aitj-main",
                              ports=[ContainerPort(name="aitj-7777",
                                                   container_port=7777)])])))
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)

        deadline = time.time() + args.timeout
        victim = "incident-demo-trainer-0"

        def pod_running_and_stepping() -> bool:
            try:
                pod = cs.pods.get("default", victim)
            except KeyError:
                return False
            if pod.status.phase != PodPhase.RUNNING:
                return False
            table = TELEMETRY.job_table(job_key)
            return bool(table and any(r["step"] > 0
                                      for r in table["replicas"]))

        while time.time() < deadline and not pod_running_and_stepping():
            time.sleep(0.05)
        if not pod_running_and_stepping():
            print("job never started stepping", file=sys.stderr)
            return 1

        print(f"preempting pod {victim} (exit 137) ...")
        sim.preempt_pod("default", victim, exit_code=137)

        def amended_bundle():
            # Amended = the first post-recovery step record extended the
            # bundle past the Running transition (workload tail attributed).
            bundles = INCIDENTS.bundles(job_key) or []
            for b in reversed(bundles):
                if (b["running_at"] is not None
                        and b["ended"] > b["running_at"]):
                    return b
            return None

        while time.time() < deadline and amended_bundle() is None:
            time.sleep(0.05)
        bundle = amended_bundle()
        if bundle is None:
            print(f"no amended incident bundle within {args.timeout}s; "
                  f"have: {INCIDENTS.bundles(job_key)}", file=sys.stderr)
            return 1

        total = bundle["downtime_ms"]
        print(f"\nincident #{bundle['id']} ({bundle['reason']}, "
              f"scope={bundle['scope']}) on {job_key}:")
        print(f"{'phase':<12}{'ms':>10}{'share':>9}")
        for phase in PHASES:
            ms = bundle["phases"][phase]
            share = (ms / total * 100.0) if total else 0.0
            print(f"{phase:<12}{ms:>10.1f}{share:>8.1f}%")
        print(f"{'total':<12}{total:>10.1f}")
        goodput_ms = GOODPUT.downtime_seconds(job_key) * 1000.0
        print(f"control window: {bundle['control_downtime_ms']:.1f} ms "
              f"(goodput ledger: {goodput_ms:.1f} ms)")
        recorded = [ev for ev in cs.events.list(None)
                    if ev.reason == constants.INCIDENT_RECORDED_REASON]
        for ev in recorded:
            print(f"event {ev.reason}: {ev.message}")

        unknown = bundle["phases"]["unknown"]
        if total > 0 and unknown > 0.05 * total:
            print(f"unattributed residue {unknown:.1f} ms exceeds 5% of "
                  f"{total:.1f} ms", file=sys.stderr)
            return 1
        if not recorded:
            print("IncidentRecorded event never fired", file=sys.stderr)
            return 1
        return 0
    finally:
        tc.stop()
        sim.stop()


if __name__ == "__main__":
    sys.exit(main())
