"""Data-plane node-chaos smoke: combined-fault survival, determinism,
flap damping, and the verified-checkpoint fallback ladder.

The ``make node-chaos-smoke`` driver (wired into ``make ci``), four legs:

1. COMBINED CHAOS, twice: subprocess fleet runs under one seed with the
   control-plane fault plane AND seeded node faults (transient flaps, a
   permanent node kill, a failure-domain kill) armed on the sim's timer
   queue, flap damping on.  Each run must converge with ZERO invariant
   violations and ZERO unattributed downtime, and at least one node fault
   of each planned kind must actually fire.  Across the two runs the plan
   digest and the final phase counts must be identical (same seed => same
   faults => same fleet state -- the repro contract of docs/CHAOS.md).
2. DAMPING A/B: the same run with ``TRAININGJOB_NODE_FLAP_GRACE_S=0``.
   Restart count under damping must be STRICTLY below the undamped run --
   the debounce has to absorb transient flaps, not just delay them.
3. CORRUPT RESUME IMAGE (``TRAININGJOB_CKPT_FAULT=resume_image``): a warm
   llama_elastic resume whose fast-path image is deterministically
   corrupted must classify the fault (``image fallback reason=corrupt``)
   and still resume from orbax at the right step.
4. CORRUPT LATEST CHECKPOINT (``TRAININGJOB_CKPT_FAULT=corrupt_latest``):
   with the fast path off, the orbax restore of the newest step is failed
   deterministically; the run must fall back to the PREVIOUS committed
   step (``restored previous committed step``) instead of dying
   (docs/RECOVERY.md integrity ladder).

Usage::

    python -m tools.node_chaos_smoke [--jobs 30] [--seed 7]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile


def _fleet_run(args: argparse.Namespace, flap_grace: float) -> dict:
    cmd = [sys.executable, "-m", "trainingjob_operator_tpu.fleet.harness",
           "--jobs", str(args.jobs), "--seed", str(args.seed),
           "--duration", str(args.duration),
           "--replicas-min", "1", "--replicas-max", "3",
           "--pods-per-node", "4", "--nodes-per-slice", "3",
           "--workers", "4", "--chaos", "--node-chaos",
           "--converge-timeout", str(args.converge_timeout), "--quiet"]
    env = dict(os.environ,
               TRAININGJOB_NODE_FLAP_GRACE_S=str(flap_grace))
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=600, env=env)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-8:]
        raise SystemExit("node-chaos fleet run failed (rc=%d):\n%s"
                         % (proc.returncode, "\n".join(tail)))
    return json.loads(proc.stdout)


def _llama_run(env_extra: dict, timeout: float = 300.0) -> str:
    env = dict(os.environ, **env_extra)
    proc = subprocess.run(
        [sys.executable, "-m",
         "trainingjob_operator_tpu.workloads.llama_elastic"],
        env=env, capture_output=True, text=True, timeout=timeout)
    if proc.returncode != 0:
        print(proc.stdout[-2000:])
        print(proc.stderr[-2000:], file=sys.stderr)
        raise SystemExit(f"llama_elastic rc={proc.returncode}")
    return proc.stdout


def _check(cond: bool, message: str) -> None:
    if not cond:
        raise SystemExit(f"node-chaos-smoke FAILED: {message}")
    print(f"ok: {message}", flush=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("node-chaos-smoke")
    parser.add_argument("--jobs", type=int, default=30)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--duration", type=float, default=2.0)
    parser.add_argument("--flap-grace", type=float, default=1.0)
    parser.add_argument("--converge-timeout", type=float, default=120.0)
    args = parser.parse_args(argv)

    # -- leg 1: combined chaos, twice, damped --------------------------------
    reports = [_fleet_run(args, args.flap_grace) for _ in range(2)]
    for i, rep in enumerate(reports):
        faults = rep["chaos"]["faults"]
        print(f"run {i}: converged={rep['converged']} "
              f"violations={len(rep['violations'])} "
              f"unattributed_ms={rep['unattributed_downtime_ms']} "
              f"restarts={rep['restarts_total']} faults={faults}")
        _check(rep["converged"] and not rep["violations"],
               f"run {i} converged with zero violations")
        _check(rep["unattributed_downtime_ms"] == 0.0,
               f"run {i} left zero downtime unattributed")
        for kind in ("node_flap", "node_down", "domain_down"):
            _check(faults.get(kind, 0) > 0,
                   f"run {i} fired at least one {kind} fault")
    a, b = reports
    _check(a["chaos"]["plan_digest"] == b["chaos"]["plan_digest"],
           "same seed produced the same chaos plan digest")
    _check(a["phase_counts"] == b["phase_counts"],
           f"same seed converged to the same phase counts "
           f"{a['phase_counts']}")

    # -- leg 2: damping A/B --------------------------------------------------
    undamped = _fleet_run(args, 0.0)
    print(f"undamped: converged={undamped['converged']} "
          f"restarts={undamped['restarts_total']}")
    _check(undamped["converged"] and not undamped["violations"],
           "undamped run still converged (flaps cost restarts, not jobs)")
    _check(a["restarts_total"] < undamped["restarts_total"],
           f"damped restarts {a['restarts_total']} strictly below "
           f"undamped {undamped['restarts_total']}")

    # -- legs 3+4: checkpoint integrity ladder -------------------------------
    ckpt = tempfile.mkdtemp(prefix="node-chaos-smoke-")
    base = {"TRAININGJOB_CHECKPOINT_DIR": ckpt,
            "TRAININGJOB_JAX_PLATFORM": "cpu",
            "LLAMA_CKPT_EVERY": "2", "LLAMA_BATCH": "2", "LLAMA_SEQ": "32"}
    cold = _llama_run(dict(base, LLAMA_STEPS="4"))
    _check("recovery_timing" in cold, "cold run seeded two committed steps")

    corrupt_image = _llama_run(dict(base, LLAMA_STEPS="6",
                                    TRAININGJOB_CKPT_FAULT="resume_image"))
    _check("image fallback reason=corrupt" in corrupt_image,
           "corrupted resume image classified as corrupt (structured reason)")
    _check("resumed at step 4" in corrupt_image,
           "corrupt-image run still resumed from orbax at step 4")

    corrupt_latest = _llama_run(dict(base, LLAMA_STEPS="8",
                                     TRAININGJOB_RESUME_OVERLAP="0",
                                     TRAININGJOB_CKPT_FAULT="corrupt_latest"))
    m = re.search(r"restored previous committed step (\d+)", corrupt_latest)
    _check(m is not None,
           "corrupt-latest run fell back to the previous committed step")
    _check("resumed at step" in corrupt_latest,
           f"corrupt-latest run resumed training from step "
           f"{m.group(1) if m else '?'}")

    print("node-chaos-smoke PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
