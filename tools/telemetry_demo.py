"""Run one simulated TrainingJob with a deliberate straggler + stall and
print the live telemetry.

The ``make telemetry-demo`` driver: in-process sim cluster (no subprocesses,
no JAX), one 4-replica job whose pods synthesize per-step telemetry records
(sim.py step annotations).  Rank 2 runs 4x slower than its peers (straggler)
and rank 3 freezes at step 25 (stall).  The demo waits for the watchdog to
fire ``StepStalled``, then prints the per-replica step table -- the same
rendering ``/debug/steps?job=...&format=text`` serves -- plus the straggler
skew and the recorded events.

Usage::

    python -m tools.telemetry_demo [--seconds 3.0]
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("telemetry-demo")
    parser.add_argument("--seconds", type=float, default=3.0,
                        help="How long to let the simulated job train.")
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="Give up if StepStalled has not fired by then.")
    args = parser.parse_args(argv)

    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.api.types import ReplicaSpec, TPUTrainingJob
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import (
        TrainingJobController,
    )
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        ObjectMeta,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.obs.telemetry import TELEMETRY
    from trainingjob_operator_tpu.runtime.sim import (
        PEAK_FLOPS_ANNOTATION,
        FLOPS_PER_STEP_ANNOTATION,
        RUN_SECONDS_ANNOTATION,
        STALL_AT_STEP_ANNOTATION,
        STALL_RANK_ANNOTATION,
        STEP_MS_ANNOTATION,
        STRAGGLER_FACTOR_ANNOTATION,
        STRAGGLER_RANK_ANNOTATION,
        TOKENS_PER_STEP_ANNOTATION,
        SimRuntime,
    )

    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.add_node("sim-0")
    sim.add_node("sim-1")
    sim.start()
    tc.run(workers=2)
    job_key = "default/telemetry-demo"
    try:
        job = TPUTrainingJob(metadata=ObjectMeta(name="telemetry-demo",
                                                 namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=4,
            template=PodTemplateSpec(
                metadata=ObjectMeta(annotations={
                    RUN_SECONDS_ANNOTATION: str(args.seconds + args.timeout),
                    STEP_MS_ANNOTATION: "20",
                    TOKENS_PER_STEP_ANNOTATION: "8192",
                    FLOPS_PER_STEP_ANNOTATION: "4e12",
                    PEAK_FLOPS_ANNOTATION: "4e14",
                    STRAGGLER_RANK_ANNOTATION: "2",
                    STRAGGLER_FACTOR_ANNOTATION: "4.0",
                    STALL_RANK_ANNOTATION: "3",
                    STALL_AT_STEP_ANNOTATION: "25",
                }),
                spec=PodSpec(containers=[
                    Container(name="aitj-main",
                              ports=[ContainerPort(name="aitj-7777",
                                                   container_port=7777)])])))
        cs.trainingjobs.create(job)

        def stalled_event():
            return [ev for ev in cs.events.list(None)
                    if ev.reason == constants.STEP_STALLED_REASON]

        deadline = time.time() + args.timeout
        time.sleep(args.seconds)
        while time.time() < deadline and not stalled_event():
            time.sleep(0.1)
        events = stalled_event()
        if not events:
            print(f"StepStalled did not fire within {args.timeout}s",
                  file=sys.stderr)
            return 1

        print(TELEMETRY.render_table(job_key))
        for ev in events:
            print(f"event {ev.reason}: {ev.message}")
        skew = TELEMETRY.straggler_skew(job_key, "trainer")
        line = TELEMETRY.status_line(job_key)
        print(f"status: {line}")
        print(f"straggler skew (slowest/median): {skew:.2f}x")
        return 0
    finally:
        tc.stop()
        sim.stop()


if __name__ == "__main__":
    sys.exit(main())
