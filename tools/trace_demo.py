"""Run one simulated TrainingJob end to end and dump its reconcile trace.

The ``make trace-demo`` driver: in-process sim cluster (no subprocesses, no
JAX), one job scripted to run ~0.3 s and succeed, then the whole reconcile
trace ring exported as Chrome ``trace_event`` JSON -- drop the output file on
https://ui.perfetto.dev (or chrome://tracing) and read the sync_job ->
reconcile_pods -> create_pod timeline visually.

Usage::

    python -m tools.trace_demo [--out /tmp/trace.json] [--run-seconds 0.3]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("trace-demo")
    parser.add_argument("--out", default="/tmp/trace.json",
                        help="Chrome trace_event JSON output path.")
    parser.add_argument("--run-seconds", default="0.3",
                        help="Simulated workload duration.")
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="Give up if the job has not finished by then.")
    args = parser.parse_args(argv)

    from trainingjob_operator_tpu.api.types import (
        ENDING_PHASES,
        ReplicaSpec,
        TPUTrainingJob,
    )
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import (
        TrainingJobController,
    )
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        ObjectMeta,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.obs.trace import TRACER
    from trainingjob_operator_tpu.runtime.sim import (
        RUN_SECONDS_ANNOTATION,
        SimRuntime,
    )

    TRACER.clear()
    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.add_node("sim-0")
    sim.start()
    tc.run(workers=2)
    try:
        job = TPUTrainingJob(metadata=ObjectMeta(name="demo",
                                                 namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=2,
            template=PodTemplateSpec(
                metadata=ObjectMeta(annotations={
                    RUN_SECONDS_ANNOTATION: args.run_seconds}),
                spec=PodSpec(containers=[
                    Container(name="aitj-main",
                              ports=[ContainerPort(name="aitj-7777",
                                                   container_port=7777)])])))
        cs.trainingjobs.create(job)
        deadline = time.time() + args.timeout
        while time.time() < deadline:
            phase = cs.trainingjobs.get("default", "demo").status.phase
            if phase in ENDING_PHASES:
                break
            time.sleep(0.05)
        else:
            print(f"job did not finish within {args.timeout}s", file=sys.stderr)
            return 1
        print(f"demo job finished: {phase}")
    finally:
        tc.stop()
        sim.stop()

    body = TRACER.export_chrome()
    with open(args.out, "w") as f:
        f.write(body)
    events = json.loads(body)["traceEvents"]
    roots = sum(1 for tr in TRACER.traces()
                if tr["root"] == "sync_job")
    print(f"wrote {args.out}: {len(events)} events across "
          f"{len(TRACER.traces())} traces ({roots} reconciles); "
          f"load it at https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
