"""On-chip perf sweep: remat policy x flash blocks x ce-chunk (Llama),
the MoE bench config, and decode throughput -- the full on-chip record in
one command.

Run on the real TPU (no args):  python tools/tune_perf.py
Prints one line per variant -- ms/step and MFU -- a WINNER line for the
Llama leg, then MoE and decode lines.  The winning settings get
baked into bench.py / workloads as defaults.

Reuses bench.py's _timed_steps so every trial inherits its guards: the
forced device-to-host fence (jax.block_until_ready does not wait on this
axon runtime; tools/repro_block_until_ready.py), the N-vs-3N scaling
cross-check, and the physical step-time floor -- a fence that silently stops
synchronizing fails the trial instead of baking a bogus WINNER into the
defaults.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    from bench import _chip_peak, _timed_steps, train_flops_per_step
    from trainingjob_operator_tpu.models import llama

    assert jax.default_backend() == "tpu", "run on the real chip"
    peak = _chip_peak()
    cfg = llama.LlamaConfig(vocab_size=32000, dim=2048, n_layers=12,
                            n_heads=16, n_kv_heads=16, ffn_dim=6144,
                            max_seq_len=2048)
    batch, seq = 8, 2048
    flops = train_flops_per_step(cfg, batch, seq)
    floor = flops / peak

    results = []

    def trial(tag, remat, bq, bk, ce_chunk=0):
        os.environ["TRAININGJOB_FA_BLOCK_Q"] = str(bq)
        os.environ["TRAININGJOB_FA_BLOCK_K"] = str(bk)
        os.environ["TRAININGJOB_CE_CHUNK"] = str(ce_chunk)
        try:
            t = _timed_steps(cfg, batch, seq, steps=4, remat=remat,
                             min_plausible_s=floor)
        except Exception as exc:
            print(json.dumps({"tag": tag, "batch": batch,
                              "error": type(exc).__name__}), flush=True)
            return
        mfu = flops / t / peak * 100
        results.append((tag, batch, t, mfu))
        print(json.dumps({"tag": tag, "batch": batch,
                          "step_ms": round(t * 1e3, 1),
                          "mfu_pct": round(mfu, 1)}), flush=True)

    # 1) remat policy sweep at default blocks
    for pol in ["full", "attn", "dots", "none"]:
        trial(f"remat={pol}", pol, 0, 0)
    if not results:
        sys.exit("all remat trials failed (see error lines above)")

    # 2) block-size sweep on the best-so-far policy
    best_pol = max(results, key=lambda r: r[3])[0].split("=")[1].split(",")[0]
    for bq, bk in [(256, 128), (512, 128), (256, 256), (512, 512),
                   (1024, 128), (128, 256)]:
        trial(f"remat={best_pol},fa={bq}x{bk}", best_pol, bq, bk)

    # 3) chunked cross-entropy at the WINNING blocks (the combined optimum
    # is what matters): frees the ~2.7 GB fp32 logits, which can unlock
    # the lighter remat policies at the full batch.
    best_tag = max(results, key=lambda r: r[3])[0]
    bq = bk = 0
    if ",fa=" in best_tag:
        bq, bk = (int(x) for x in best_tag.split(",fa=")[1].split("x"))
    for chunk in (256, 512):
        trial(f"remat={best_pol},fa={bq or 128}x{bk or 128},ce={chunk}",
              best_pol, bq, bk, ce_chunk=chunk)
        if best_pol != "dots":
            trial(f"remat=dots,fa={bq or 128}x{bk or 128},ce={chunk}",
                  "dots", bq, bk, ce_chunk=chunk)

    tag, b, t, mfu = max(results, key=lambda r: r[3])
    print(json.dumps({"winner": tag, "batch": b,
                      "step_ms": round(t * 1e3, 1),
                      "mfu_pct": round(mfu, 1)}), flush=True)

    # 4) MoE timing at the bench MoE config (active-params MFU basis) and
    # the serving-side decode numbers -- the rest of the on-chip record
    # (VERDICT r4 #3/#6), printed as labeled JSON lines.  (The old
    # router-group sweep died with the config knob: grouped dispatch lost
    # its A/B once the dense-dispatch cost went linear in T by default.)
    from bench import _timed_steps_moe, bench_decode, moe_train_flops_per_step
    from trainingjob_operator_tpu.models import moe as moe_mod

    moe_cfg = moe_mod.MoEConfig(vocab_size=32000, dim=1024, n_layers=6,
                                n_heads=16, n_kv_heads=8, ffn_dim=2816,
                                n_experts=8, experts_per_token=2,
                                max_seq_len=2048)
    mb, mseq = 8, 2048
    mflops = moe_train_flops_per_step(moe_cfg, mb, mseq)
    try:
        t = _timed_steps_moe(moe_cfg, mb, mseq, steps=3, remat="attn",
                             min_plausible_s=mflops / peak)
        print(json.dumps({"moe_step_ms": round(t * 1e3, 1),
                          "mfu_pct": round(
                              mflops / t / peak * 100, 1)}), flush=True)
    except Exception as exc:
        print(json.dumps({"moe_error": type(exc).__name__}), flush=True)
    try:
        print(json.dumps({"decode": bench_decode(True)}), flush=True)
    except Exception as exc:
        print(json.dumps({"decode_error": type(exc).__name__}), flush=True)


if __name__ == "__main__":
    sys.exit(main() or 0)
