"""Run the continuous-batching decode service against open-loop synthetic
traffic and assert the serving plane's contract end to end.

The ``make serve-smoke`` driver (wired into ``make ci``): a tiny-config
llama DecodeService on CPU, driven through a mixed prompt/output-length
Poisson trace (workloads/serve.py).  Gates:

- every submitted request completes (>0 completed, none lost);
- ZERO stale-KV violations: identical requests decode identically no
  matter which slot they landed in or what occupied it before -- the
  per-slot cache paging contract (greedy decode makes any divergence a
  leak, not noise);
- backpressure is explicit: an over-capacity burst raises QueueFull
  instead of growing the queue toward OOM;
- p99 token latency stays under a deliberately generous bound -- the
  smoke catches a scheduler that stopped interleaving (seconds-long
  stalls), not CPU jitter.

Usage::

    python -m tools.serve_smoke [--requests 32] [--p99-ms 30000]
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("serve-smoke")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--p99-ms", type=float, default=30000.0,
                        help="Generous p99 token-latency bound (ms).")
    args = parser.parse_args(argv)

    import jax

    from trainingjob_operator_tpu.models import llama
    from trainingjob_operator_tpu.workloads import serve

    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    svc = serve.DecodeService(params, cfg, slots=args.slots,
                              prefill_chunk=8,
                              queue_cap=max(args.requests, 64))

    # Backpressure first, while the queue is empty: fill to capacity, then
    # one more must raise -- explicitly, not via memory growth.
    probe = serve.DecodeService(params, cfg, slots=2, prefill_chunk=8,
                                queue_cap=4)
    for _ in range(4):
        probe.submit([1, 2, 3], 2)
    try:
        probe.submit([1, 2, 3], 2)
        print("queue over capacity did not raise QueueFull", file=sys.stderr)
        return 1
    except serve.QueueFull:
        pass
    # The rejection must be visible on the wire too, not just as an
    # exception the caller happened to catch (docs/OBSERVABILITY.md).
    from trainingjob_operator_tpu.utils.metrics import METRICS
    rejected = METRICS.snapshot().get(
        'trainingjob_serve_rejected_total'
        '{job="local/serve",reason="QueueFull"}', 0)
    if not rejected:
        print("QueueFull raised but trainingjob_serve_rejected_total "
              "did not count it", file=sys.stderr)
        return 1
    print(f"backpressure ok: QueueFull at capacity 4 "
          f"(rejected_total={rejected:.0f})")

    traffic = serve.synthetic_traffic(
        args.requests, seed=11, rate=1.5, vocab=cfg.vocab_size,
        prompt_lens=(4, 16), out_tokens=(2, 32))
    result = serve.run_traffic(svc, traffic)
    s = result["stats"]
    print(f"completed={s['completed_total']}/{s['submitted']} "
          f"ticks={s['steps']} tokens={s['tokens_total']} "
          f"tokens/s={s['aggregate_tokens_per_sec']} "
          f"p50={s['token_latency_ms_p50']}ms "
          f"p99={s['token_latency_ms_p99']}ms "
          f"ttft_p50={s['ttft_ms_p50']}ms "
          f"stale_kv_violations={s['stale_kv_violations']}")

    if s["completed_total"] <= 0 or s["completed_total"] != s["submitted"]:
        print(f"lost requests: {s['completed_total']} of {s['submitted']}",
              file=sys.stderr)
        return 1
    if s["stale_kv_violations"]:
        print(f"{s['stale_kv_violations']} stale-KV violations: slot "
              f"paging leaked state across requests", file=sys.stderr)
        return 1
    if s["token_latency_ms_p99"] > args.p99_ms:
        print(f"p99 token latency {s['token_latency_ms_p99']} ms exceeds "
              f"{args.p99_ms} ms: the scheduler is stalling",
              file=sys.stderr)
        return 1
    print("serve smoke ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
