"""Drive one injected fault per fallback-ladder rung through a REAL
llama_elastic survivor and assert the ladder degraded in order.

The ``make resize-smoke`` driver for the live re-rendezvous path
(docs/ELASTIC.md).  Three subprocess runs of the real workload, each
shrunk mid-run by the parent playing the controller (atomic
``generation.json`` publish, same bytes as ``publish_generation``):

1. no fault          -> rung ``live``: the SAME process resizes in place
                        and finishes rc 0;
2. ``barrier`` fault -> rung ``checkpoint``: degrades exactly one rung,
                        commits a checkpoint, exits 143;
3. ``barrier,persist`` faults -> rung ``restart_all``: the checkpoint
                        rung itself fails, the survivor still exits 143
                        (never wedges) -- and the ``resize_rung`` lines
                        prove checkpoint was attempted BEFORE restart_all
                        (ladder order).

A fourth, in-process scenario replays a degraded resize through the sim
runtime + real controller and asserts the incident bundle stamps the
``checkpoint`` rung with zero unattributed downtime -- the degraded
counterpart of ``tools/elastic_smoke.py``'s live-rung attribution check.

Usage::

    python -m tools.resize_smoke [--timeout 240]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time


def run_child(env, timeout, rdv_dir):
    """Run llama_elastic, publishing the shrink generation after the first
    completed-step line (the parent is the controller here)."""
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "trainingjob_operator_tpu.workloads.llama_elastic"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        killer = threading.Timer(timeout, proc.kill)
        killer.start()
        lines = []
        wrote = False
        try:
            for raw in proc.stdout:
                lines.append(raw.rstrip("\n"))
                if not wrote and re.match(r"step \d+/", lines[-1]):
                    os.makedirs(rdv_dir, exist_ok=True)
                    tmp = os.path.join(rdv_dir, ".generation.tmp")
                    with open(tmp, "w", encoding="utf-8") as fh:
                        json.dump({"generation": 1, "world": [0, 1]}, fh)
                    os.replace(tmp, os.path.join(rdv_dir, "generation.json"))
                    wrote = True
            rc = proc.wait()
        finally:
            killer.cancel()
    finally:
        proc.kill()
        proc.wait()
    return rc, lines


def rung_lines(lines):
    out = []
    for line in lines:
        m = re.match(r"resize_rung generation=(\d+) rung=(\w+) "
                     r"phase=([\w-]+)(?: injected=(\d))?", line)
        if m:
            out.append((int(m.group(1)), m.group(2), m.group(3),
                        m.group(4)))
    return out


def ladder_case(name, root, fault, want_rc, want_rungs, timeout):
    d = os.path.join(root, name)
    rdv_dir = os.path.join(d, "rdv")
    xla = (os.environ.get("XLA_FLAGS", "")
           + " --xla_force_host_platform_device_count=8")
    env = dict(os.environ, LLAMA_STEPS="6", LLAMA_CKPT_EVERY="2",
               LLAMA_BATCH="8", LLAMA_SEQ="32",
               XLA_FLAGS=xla.strip(), JAX_PLATFORMS="cpu",
               TRAININGJOB_JAX_PLATFORM="cpu",
               TRAININGJOB_CHECKPOINT_DIR=os.path.join(d, "ckpt"),
               TRAININGJOB_ELASTIC_REPLICAS="4",
               TRAININGJOB_RESIZE_DIR=rdv_dir,
               TRAININGJOB_RESIZE_POLL_S="0.05",
               TRAININGJOB_RESIZE_FAULT=fault)
    rc, lines = run_child(env, timeout, rdv_dir)
    rungs = [(r, p) for _, r, p, _ in rung_lines(lines)]
    print(f"[{name}] fault={fault!r} rc={rc} rungs={rungs}")
    if rc != want_rc:
        tail = "\n".join(lines[-8:])
        print(f"[{name}] expected rc {want_rc}, got {rc}:\n{tail}",
              file=sys.stderr)
        return False
    if [r for r, _ in rungs] != want_rungs:
        print(f"[{name}] expected ladder {want_rungs}, got {rungs}",
              file=sys.stderr)
        return False
    if fault and any(inj is not None and inj != "1"
                     for _gen, _rung, _phase, inj in rung_lines(lines)):
        print(f"[{name}] injected fault not marked injected=1",
              file=sys.stderr)
        return False
    return True


def degraded_attribution(timeout):
    """Sim + real controller: a resize whose survivor reports the
    ``checkpoint`` rung must stamp it on the incident bundle and still
    attribute every millisecond (zero ``unknown``)."""
    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.api.types import (
        ReplicaSpec,
        RestartPolicy,
        RestartScope,
        TPUTrainingJob,
        TrainingJobPhase,
    )
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import (
        TrainingJobController,
    )
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        EnvVar,
        ObjectMeta,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.obs.incident import INCIDENTS
    from trainingjob_operator_tpu.runtime.sim import (
        RENDEZVOUS_MS_ANNOTATION,
        RENDEZVOUS_RUNG_ANNOTATION,
        RUN_SECONDS_ANNOTATION,
        STEP_MS_ANNOTATION,
        SimRuntime,
    )

    def wait_for(pred, budget):
        deadline = time.time() + budget
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.add_node("sim-0")
    sim.start()
    tc.run(workers=2)
    name = "resize-smoke"
    key = f"default/{name}"
    rdv_dir = tempfile.mkdtemp(prefix="resize-smoke-rdv-")
    try:
        INCIDENTS.forget(key)
        job = TPUTrainingJob(metadata=ObjectMeta(name=name,
                                                 namespace="default"))
        template = PodTemplateSpec(
            metadata=ObjectMeta(
                annotations={
                    RUN_SECONDS_ANNOTATION: str(timeout * 2),
                    STEP_MS_ANNOTATION: "20",
                    # The survivors report a DEGRADED rebootstrap: the
                    # bundle must fall through to the generic restart
                    # attribution and still account for every ms.
                    RENDEZVOUS_MS_ANNOTATION: "10",
                    RENDEZVOUS_RUNG_ANNOTATION: "checkpoint",
                }),
            spec=PodSpec(containers=[
                Container(name="aitj-main",
                          env=[EnvVar(name=constants.RESIZE_DIR_ENV,
                                      value=rdv_dir)],
                          ports=[ContainerPort(name="aitj-7777",
                                               container_port=7777)])]))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=3, min_replicas=1, template=template,
            restart_policy=RestartPolicy.EXIT_CODE,
            restart_scope=RestartScope.RESIZE)
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)

        def phase():
            return cs.trainingjobs.get("default", name).status.phase

        if not wait_for(lambda: phase() == TrainingJobPhase.RUNNING,
                        timeout):
            print(f"[sim] job never reached Running (phase {phase()})",
                  file=sys.stderr)
            return False
        sim.preempt_pod("default", f"{name}-trainer-1", exit_code=137)

        def stamped_bundle():
            for b in reversed(INCIDENTS.bundles(key) or []):
                if (b["running_at"] is not None
                        and b["ended"] > b["running_at"]
                        and b.get("rung") is not None):
                    return b
            return None

        if not wait_for(lambda: stamped_bundle() is not None, timeout):
            print(f"[sim] no bundle with a rung stamp; have: "
                  f"{INCIDENTS.bundles(key)}", file=sys.stderr)
            return False
        bundle = stamped_bundle()
        print(f"[sim] rung={bundle['rung']} "
              f"downtime_ms={bundle['downtime_ms']:.1f} "
              f"unknown_ms={bundle['phases']['unknown']:.1f}")
        if bundle["rung"] != "checkpoint":
            print(f"[sim] rung {bundle['rung']!r} != 'checkpoint'",
                  file=sys.stderr)
            return False
        if bundle["phases"]["unknown"] != 0.0:
            print(f"[sim] unattributed residue "
                  f"{bundle['phases']['unknown']:.1f} ms", file=sys.stderr)
            return False
        return True
    finally:
        tc.stop()
        sim.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("resize-smoke")
    parser.add_argument("--timeout", type=float, default=240.0,
                        help="Per-subprocess budget.")
    args = parser.parse_args(argv)

    root = tempfile.mkdtemp(prefix="resize-smoke-")
    ok = True
    # One injected fault per rung, in ladder order.  The live case proves
    # the default path stays live; the barrier case proves one failure
    # degrades exactly one rung; the double-fault case proves even a
    # failing checkpoint rung exits instead of wedging, and that the rungs
    # were attempted in order.
    ok &= ladder_case("live", root, fault="", want_rc=0,
                      want_rungs=["live"], timeout=args.timeout)
    ok &= ladder_case("checkpoint", root, fault="barrier", want_rc=143,
                      want_rungs=["checkpoint"], timeout=args.timeout)
    ok &= ladder_case("restart-all", root, fault="barrier,persist",
                      want_rc=143,
                      want_rungs=["checkpoint", "restart_all"],
                      timeout=args.timeout)
    ok &= degraded_attribution(min(args.timeout, 30.0))
    print("resize-smoke: " + ("OK" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
