"""Seeded chaos churn run, twice, asserting survival AND determinism.

The ``make chaos-smoke`` driver (wired into ``make ci``): two subprocess
runs of the fleet harness under the same churn seed and the same chaos
seed (docs/CHAOS.md).  Subprocesses, not in-process runs: the incident
recorder and metrics registry are process-global, so only a fresh
interpreter gives each run the clean slate the byte-determinism check
needs.  Gates, per run:

- the fleet converges with ZERO invariant violations -- retries, relists
  and quarantine must absorb every injected fault;
- ZERO unattributed downtime: every ms the flight recorder cannot place
  in a named phase must fall inside a declared chaos window;
- at least one fault of an API kind actually fired (a chaos run that
  injected nothing proves nothing).

Across the two runs:

- identical chaos plan digest: the seed fully determines the fault
  schedule (the reproducibility contract -- a failure seed IS the repro);
- identical final phase counts: the hardened reconcile path converges to
  the same fleet state no matter how the faults interleave.

Usage::

    python -m tools.chaos_smoke [--jobs 60] [--seed 0] [--chaos-seed 0]
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys


def _run(args: argparse.Namespace) -> dict:
    cmd = [sys.executable, "-m", "trainingjob_operator_tpu.fleet.harness",
           "--jobs", str(args.jobs), "--seed", str(args.seed),
           "--duration", str(args.duration),
           "--replicas-min", "1", "--replicas-max", "4",
           "--workers", "4", "--chaos",
           "--chaos-seed", str(args.chaos_seed),
           "--converge-timeout", str(args.converge_timeout), "--quiet"]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=600)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-8:]
        raise SystemExit("chaos fleet run failed (rc=%d):\n%s"
                         % (proc.returncode, "\n".join(tail)))
    return json.loads(proc.stdout)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("chaos-smoke")
    parser.add_argument("--jobs", type=int, default=60)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=2.0)
    parser.add_argument("--converge-timeout", type=float, default=120.0)
    args = parser.parse_args(argv)

    reports = [_run(args) for _ in range(2)]
    for i, rep in enumerate(reports):
        faults = rep["chaos"]["faults"]
        api_faults = sum(faults.get(k, 0)
                         for k in ("unavailable", "timeout", "conflict"))
        print(f"run {i}: converged={rep['converged']} "
              f"violations={len(rep['violations'])} "
              f"unattributed_ms={rep['unattributed_downtime_ms']} "
              f"api_retries={rep['api_retries_total']} "
              f"faults={faults}")
        if not rep["converged"] or rep["violations"]:
            print("chaos run did not converge cleanly:\n"
                  + "\n".join(rep["violations"][:10]), file=sys.stderr)
            return 1
        if rep["unattributed_downtime_ms"] > 0.0:
            print(f"run {i}: {rep['unattributed_downtime_ms']} ms of "
                  f"downtime left unattributed under chaos",
                  file=sys.stderr)
            return 1
        if api_faults == 0:
            print(f"run {i}: chaos plane injected no API faults -- the "
                  f"smoke proved nothing", file=sys.stderr)
            return 1

    a, b = reports
    if a["chaos"]["plan_digest"] != b["chaos"]["plan_digest"]:
        print("same chaos seed produced different plan digests:\n"
              f"  {a['chaos']['plan_digest']}\n  {b['chaos']['plan_digest']}",
              file=sys.stderr)
        return 1
    if a["phase_counts"] != b["phase_counts"]:
        print("same seeds converged to different phase counts:\n"
              f"  {a['phase_counts']}\n  {b['phase_counts']}",
              file=sys.stderr)
        return 1
    print(f"chaos smoke ok: plan {a['chaos']['plan_digest'][:12]} "
          f"phase_counts={a['phase_counts']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
