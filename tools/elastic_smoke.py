"""Run one simulated scope=Resize TrainingJob through a shrink and assert
the elastic fast path end to end: survivor keepalive + phase attribution.

The ``make elastic-smoke`` driver: in-process sim cluster, one 3-replica
job with ``restartScope: Resize``.  Once it is Running the smoke preempts
one replica (exit 137) and checks the whole contract:

- the drain deletes ONLY the failed pod -- the survivors keep their uids
  (no restart-all);
- the job converges back to Running at the narrower width, with the
  bumped rendezvous generation (new world + surviving hosts) atomically
  republished into the resize dir for the survivors to pick up;
- the incident flight recorder attributes the window to the resize
  phases (``detect``/``rendezvous``/``reshard``/``first_step``) with the
  live-rebootstrap rung stamped on the bundle, zero ``teardown`` and zero
  unattributed residue -- printed as the same phase table
  ``/debug/incidents?job=...`` serves.

Usage::

    python -m tools.elastic_smoke [--timeout 30]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elastic-smoke")
    parser.add_argument("--timeout", type=float, default=30.0,
                        help="Give up if the resize has not converged.")
    args = parser.parse_args(argv)

    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.api.types import (
        ReplicaSpec,
        RestartPolicy,
        RestartScope,
        TPUTrainingJob,
        TrainingJobPhase,
    )
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import (
        TrainingJobController,
    )
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        EnvVar,
        ObjectMeta,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.obs.incident import INCIDENTS, PHASES
    from trainingjob_operator_tpu.runtime.sim import (
        RENDEZVOUS_MS_ANNOTATION,
        RUN_SECONDS_ANNOTATION,
        STEP_MS_ANNOTATION,
        TOKENS_PER_STEP_ANNOTATION,
        SimRuntime,
    )
    from trainingjob_operator_tpu.workloads import rendezvous

    def wait_for(pred, timeout):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.add_node("sim-0")
    sim.start()
    tc.run(workers=2)
    name = "elastic-smoke"
    key = f"default/{name}"
    rdv_dir = tempfile.mkdtemp(prefix="elastic-smoke-rdv-")
    try:
        INCIDENTS.forget(key)
        job = TPUTrainingJob(metadata=ObjectMeta(name=name,
                                                 namespace="default"))
        template = PodTemplateSpec(
            metadata=ObjectMeta(
                annotations={
                    RUN_SECONDS_ANNOTATION: str(args.timeout * 2),
                    # Survivors report steps: the first step record after
                    # the resize amends the bundle with the workload tail.
                    STEP_MS_ANNOTATION: "20",
                    TOKENS_PER_STEP_ANNOTATION: "8192",
                    # ... and a live-rebootstrap record once the bumped
                    # generation lands, so the bundle gets a rendezvous
                    # slice and a rung stamp.
                    RENDEZVOUS_MS_ANNOTATION: "15",
                }),
            spec=PodSpec(containers=[
                Container(name="aitj-main",
                          env=[EnvVar(name=constants.RESIZE_DIR_ENV,
                                      value=rdv_dir)],
                          ports=[ContainerPort(name="aitj-7777",
                                               container_port=7777)])]))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=3, min_replicas=1, template=template,
            restart_policy=RestartPolicy.EXIT_CODE,
            restart_scope=RestartScope.RESIZE)
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)

        def phase():
            return cs.trainingjobs.get("default", name).status.phase

        if not wait_for(lambda: phase() == TrainingJobPhase.RUNNING,
                        args.timeout):
            print(f"job never reached Running (phase {phase()})",
                  file=sys.stderr)
            return 1
        before = {p.metadata.name: p.metadata.uid
                  for p in cs.pods.list("default")}
        victim = f"{name}-trainer-1"
        print(f"running at width 3; preempting {victim} (exit 137) ...")
        sim.preempt_pod("default", victim, exit_code=137)

        def resized():
            job = cs.trainingjobs.get("default", name)
            return (job.status.rendezvous_generation == 1
                    and job.status.phase == TrainingJobPhase.RUNNING
                    and len(cs.pods.list("default")) == 2)

        if not wait_for(resized, args.timeout):
            job = cs.trainingjobs.get("default", name)
            print(f"resize never converged: phase={job.status.phase} "
                  f"generation={job.status.rendezvous_generation} "
                  f"pods={len(cs.pods.list('default'))}", file=sys.stderr)
            return 1

        # Survivor keepalive: the two remaining pods are the SAME pods
        # (uid-identical), not replacements.
        after = {p.metadata.name: p.metadata.uid
                 for p in cs.pods.list("default")}
        expected = {n: u for n, u in before.items() if n != victim}
        if after != expected:
            print(f"survivors were restarted: before={expected} "
                  f"after={after}", file=sys.stderr)
            return 1
        print(f"survivors kept alive: {sorted(after)} (uids unchanged)")

        doc = rendezvous.read_generation(
            os.path.join(rdv_dir, "generation.json"))
        if doc is None or doc["generation"] != 1 or doc["world"] != [0, 2]:
            print(f"bad republished generation doc: {doc}", file=sys.stderr)
            return 1
        print(f"generation {doc['generation']} republished: "
              f"world {doc['world']}, {len(doc['hosts'])} hosts")

        def amended_bundle():
            bundles = INCIDENTS.bundles(key) or []
            for b in reversed(bundles):
                if (b["running_at"] is not None
                        and b["ended"] > b["running_at"]
                        and b.get("rung") is not None):
                    return b
            return None

        if not wait_for(lambda: amended_bundle() is not None, args.timeout):
            print(f"no amended incident bundle with a rendezvous rung; "
                  f"have: {INCIDENTS.bundles(key)}", file=sys.stderr)
            return 1
        bundle = amended_bundle()
        total = bundle["downtime_ms"]
        print(f"\nincident #{bundle['id']} ({bundle['reason']}, "
              f"kind={bundle['kind']}) on {key}:")
        print(f"{'phase':<12}{'ms':>10}{'share':>9}")
        for ph in PHASES:
            ms = bundle["phases"][ph]
            share = (ms / total * 100.0) if total else 0.0
            print(f"{ph:<12}{ms:>10.1f}{share:>8.1f}%")
        print(f"{'total':<12}{total:>10.1f}")

        if bundle["kind"] != "resize":
            print(f"bundle kind {bundle['kind']!r} != 'resize'",
                  file=sys.stderr)
            return 1
        if bundle["rung"] != "live":
            print(f"bundle rung {bundle['rung']!r} != 'live'",
                  file=sys.stderr)
            return 1
        if bundle["phases"]["rendezvous"] <= 0.0:
            print("rendezvous phase not attributed: "
                  f"{bundle['phases']['rendezvous']:.1f} ms",
                  file=sys.stderr)
            return 1
        if bundle["phases"]["teardown"] != 0.0:
            print("survivors were torn down: teardown phase "
                  f"{bundle['phases']['teardown']:.1f} ms", file=sys.stderr)
            return 1
        if bundle["phases"]["unknown"] != 0.0:
            print(f"unattributed residue {bundle['phases']['unknown']:.1f} "
                  f"ms", file=sys.stderr)
            return 1
        return 0
    finally:
        tc.stop()
        sim.stop()


if __name__ == "__main__":
    sys.exit(main())
