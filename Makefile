# CI recipe: `make ci` = the full gate (lint + tests + multichip dryrun +
# compile check).  The virtual 8-device CPU mesh stands in for multi-chip TPU
# (SURVEY.md §7); bench runs on real hardware out-of-band.

PY ?= python
VDEV ?= 8

.PHONY: lint test dryrun bench install ci

# AST-based operator lint (docs/STATIC_ANALYSIS.md): milliseconds, runs
# before the tests so a grammar/race/contract bug fails fast with a
# file:line annotation instead of 5 modules of collection errors.
lint:
	$(PY) -m tools.analyze trainingjob_operator_tpu/ tools/ tests/ --format=github

test:
	$(PY) -m pytest tests/ -q

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=$(VDEV) \
	JAX_PLATFORMS=cpu DRYRUN_DEVICES=$(VDEV) $(PY) __graft_entry__.py

bench:
	$(PY) bench.py

install:
	$(PY) -m pip install -e . --no-build-isolation

ci: lint test dryrun
