# CI recipe: `make ci` = the full gate (lint + tests + multichip dryrun +
# compile check).  The virtual 8-device CPU mesh stands in for multi-chip TPU
# (SURVEY.md §7); bench runs on real hardware out-of-band.

PY ?= python
VDEV ?= 8

.PHONY: lint lint-diff lint-sarif shard-state-report thread-model-report test test-slow dryrun bench install ci trace-demo telemetry-demo incident-demo fleet-smoke chaos-smoke node-chaos-smoke recovery-smoke elastic-smoke serve-smoke resize-smoke slo-smoke request-obs-smoke

# AST-based operator lint (docs/STATIC_ANALYSIS.md): runs before the tests
# so a grammar/race/contract bug fails fast with a file:line annotation
# instead of 5 modules of collection errors. --max-seconds 2 is a wall-clock
# budget: the whole-program graph must stay cheap, and a perf regression in
# it should fail CI, not silently slow every push.
lint:
	$(PY) -m tools.analyze trainingjob_operator_tpu/ tools/ tests/ bench.py --format=github --max-seconds 2

# Pre-commit loop: lint only files whose AST changed vs. the given ref
# (default HEAD).  Project-graph passes still see the whole tree, so an
# interprocedural regression introduced by a changed file is caught; an
# unchanged file's pre-existing findings are not re-reported.
LINT_REF ?= HEAD
lint-diff:
	$(PY) -m tools.analyze trainingjob_operator_tpu/ tools/ tests/ bench.py --changed-since $(LINT_REF) --format=github

# SARIF artifact for code-scanning upload; written by `make ci` alongside
# the human-readable gate (exit code still enforced by the lint target).
lint-sarif:
	$(PY) -m tools.analyze trainingjob_operator_tpu/ tools/ tests/ bench.py --format=sarif > analyze.sarif || true
	@echo "wrote analyze.sarif"

# Machine-readable shard-state inventory (TJA027): the module-level
# mutable-singleton ledger ROADMAP item 3 consumes.  Fails when any
# singleton is unclassified, a registry entry is stale, or something
# mutates a constant-classified singleton -- i.e. on any shard-hostile
# write pattern outside the declared registry.
shard-state-report:
	$(PY) -m tools.analyze --report shard-state > shard_state.json
	@echo "wrote shard_state.json"

# Machine-readable concurrency model (TJA028-TJA032): thread roles and
# closures, the may-happen-in-parallel matrix, and per-singleton access
# evidence (site, via, roles, lock-set) -- the thread-model companion to
# the shard-state inventory.  Fails when any of the five concurrency
# passes has unwaived findings.
thread-model-report:
	$(PY) -m tools.analyze --report thread-model > thread_model.json
	@echo "wrote thread_model.json"

# Fast suite: the 10k-job fleet run (tests/test_fleet.py) hides behind the
# slow marker; `make test-slow` opts in.
test:
	$(PY) -m pytest tests/ -q -m 'not slow'

test-slow:
	$(PY) -m pytest tests/ -q -m slow

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=$(VDEV) \
	JAX_PLATFORMS=cpu DRYRUN_DEVICES=$(VDEV) $(PY) __graft_entry__.py

bench:
	$(PY) bench.py

# One simulated job end to end; writes the reconcile trace as Chrome
# trace_event JSON (docs/OBSERVABILITY.md) -- load it in Perfetto.
trace-demo:
	$(PY) -m tools.trace_demo --out /tmp/trace.json

# One simulated job with a deliberate straggler + stalled replica; prints the
# live per-replica step table, straggler skew, and the StepStalled event
# (docs/OBSERVABILITY.md telemetry section).
telemetry-demo:
	$(PY) -m tools.telemetry_demo

# One simulated job through a scripted preemption (pod killed with exit 137,
# restart scope ALL); prints the incident flight recorder's phase-attributed
# downtime table and cross-checks it against the goodput ledger
# (docs/OBSERVABILITY.md incident section).  Exits non-zero if any downtime
# stays unattributed.
incident-demo:
	$(PY) -m tools.incident_demo

# Seeded ~200-job churn run against the sim cluster (docs/FLEET.md); exits
# non-zero unless the fleet converges with zero invariant violations.
# TRAININGJOB_FLEET_SEED / TRAININGJOB_FLEET_JOBS override the defaults.
# The wall ceiling is 2x the event-kernel baseline (~12 s on the one-core
# CI box): a run past it files a violation -- the tripwire for a sim-kernel
# (or control-plane) throughput regression.
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m trainingjob_operator_tpu.fleet.harness \
		--jobs $${TRAININGJOB_FLEET_JOBS:-200} \
		--seed $${TRAININGJOB_FLEET_SEED:-0} \
		--duration 3 --replicas-min 1 --replicas-max 4 --workers 4 \
		--max-wall-seconds 24 --quiet

# Two same-seed churn runs under the seeded control-plane chaos plane
# (docs/CHAOS.md): each must converge with zero violations and zero
# unattributed downtime while API errors/timeouts/conflicts, latency
# spikes, watch drops and stale lists are injected; across the runs the
# chaos plan digest and the final phase counts must be identical (the
# seed-is-the-repro determinism contract).
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.chaos_smoke

# Data-plane failure domains (docs/CHAOS.md): two same-seed combined-chaos
# runs (control-plane faults + seeded node flaps/kills/domain kills) must
# converge with zero violations, zero unattributed downtime and identical
# plan digest + phase counts; a flap-grace-0 A/B run must restart strictly
# more than the damped run; and deterministically corrupted checkpoints
# (TRAININGJOB_CKPT_FAULT) must classify the fault and fall back to the
# previous committed step (docs/RECOVERY.md integrity ladder).
node-chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.node_chaos_smoke

# Cold run -> serial warm resume -> overlapped warm resume at tiny shapes
# (docs/RECOVERY.md); exits non-zero unless both resume paths work and
# report their phase breakdowns.  The measured 124M version is bench.py's
# time_to_resume_training leg.
recovery-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.recovery_smoke

# One scope=Resize job through a shrink on the sim cluster
# (docs/ELASTIC.md): survivors must keep their uids, the bumped rendezvous
# generation must be republished, and the incident bundle must attribute
# the window to detect/reshard/first_step with zero teardown and zero
# unattributed residue.
elastic-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.elastic_smoke

# The continuous-batching decode service against open-loop synthetic
# traffic (docs/SERVING.md): every request completes, zero stale-KV
# violations (slot paging never leaks across requests), explicit
# QueueFull backpressure, p99 token latency under a generous bound.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.serve_smoke

# The re-rendezvous fallback ladder end to end (docs/ELASTIC.md): real
# llama_elastic survivors shrunk mid-run, one injected fault per rung
# (TRAININGJOB_RESIZE_FAULT), asserting live -> checkpoint -> restart_all
# degrade IN ORDER and a degraded resize still attributes every ms of
# downtime (rung stamped on the incident bundle, zero unknown).
resize-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.resize_smoke

# Fleet SLO plane (docs/SLO.md): a healthy seeded chaos fleet must raise
# ZERO breaches (false-positive gate) with the profiler attributing >=90%
# of reconcile CPU to named spans at <5% overhead; the same seeds with the
# plane off must produce byte-identical plan digest + phase counts (the
# plane observes, never perturbs); a latency-degraded arm must raise >=1
# breach, emit the SLOBreach event and stamp the incident bundle.
slo-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.slo_smoke

# Request-lifecycle plane (docs/SERVING.md): a real DecodeService over the
# TCP telemetry wire (completed + rejected + drain-evicted, zero orphans
# after reconcile); a churn fleet with scale-in deletes and exit-137
# restarts must converge with zero orphaned requests and every restart
# incident bundle carrying a requests stanza; the same seeds with the
# plane off must produce byte-identical plan digest + phase counts.
request-obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.request_obs_smoke

install:
	$(PY) -m pip install -e . --no-build-isolation

ci: lint lint-sarif shard-state-report thread-model-report test dryrun incident-demo fleet-smoke chaos-smoke node-chaos-smoke recovery-smoke elastic-smoke serve-smoke resize-smoke slo-smoke request-obs-smoke
