# CI recipe: `make ci` = the full gate (tests + multichip dryrun + compile
# check).  The virtual 8-device CPU mesh stands in for multi-chip TPU
# (SURVEY.md §7); bench runs on real hardware out-of-band.

PY ?= python
VDEV ?= 8

.PHONY: test dryrun bench install ci

test:
	$(PY) -m pytest tests/ -q

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=$(VDEV) \
	JAX_PLATFORMS=cpu DRYRUN_DEVICES=$(VDEV) $(PY) __graft_entry__.py

bench:
	$(PY) bench.py

install:
	$(PY) -m pip install -e . --no-build-isolation

ci: test dryrun
